"""``mx.npx`` — operators that extend NumPy (neural-net ops, device utils).

Reference analog: ``python/mxnet/numpy_extension/`` — the `_npx_*` op
namespace (batch_norm, convolution, topk, …) plus np-mode switches and
device helpers.  Ops resolve through the same registry as ``mx.nd``; because
dispatch preserves the array flavor, calling these on ``mx.np.ndarray``
inputs yields ``mx.np.ndarray`` outputs.
"""
from __future__ import annotations

import sys as _sys

from ..context import cpu, current_context, gpu, num_gpus, num_tpus, tpu
from ..ndarray.register import make_op_func
from ..ndarray.utils import load, save
from ..ops import registry as _registry
from ..random import seed
from ..util import (is_np_array, is_np_default_dtype, is_np_shape, reset_np,
                    set_np, use_np, use_np_array, use_np_shape)

_this = _sys.modules[__name__]

# npx name -> registry op name (reference _npx_* ops map onto the same
# kernels as the legacy nd ops; here literally the same OpSchema)
_ALIASES = {
    "relu": "relu",
    "sigmoid": "sigmoid",
    "log_sigmoid": "log_sigmoid",
    "softmax": "softmax",
    "log_softmax": "log_softmax",
    "masked_softmax": "softmax",
    "activation": "Activation",
    "leaky_relu": "LeakyReLU",
    "batch_norm": "BatchNorm",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "fully_connected": "FullyConnected",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "pooling": "Pooling",
    "dropout": "Dropout",
    "one_hot": "one_hot",
    "pick": "pick",
    "topk": "topk",
    "batch_dot": "batch_dot",
    "gather_nd": "gather_nd",
    "scatter_nd": "scatter_nd",
    "embedding": "embedding",
    "arange_like": "arange_like",
    "sequence_mask": "sequence_mask",
    "smooth_l1": "smooth_l1",
    "gamma": "random_gamma",
    "reshape_like": "reshape",
    "slice": "slice",
    "shape_array": "shape_array",
    "multibox_detection": None,
    "index_update": None,
    "index_add": None,
    "ctc_loss": "CTCLoss",
    "erf": None,
    "erfinv": None,
    "broadcast_like": "broadcast_to",
    "constraint_check": None,
    "rnn": "_rnn_fused",
    "reshape": "npx_reshape",
    "batch_flatten": "flatten",
    "slice_axis": "slice_axis",
    "intgemm_fully_connected": "FullyConnected",
    "interleaved_matmul_selfatt_qk": "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt": "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk": "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt": "interleaved_matmul_encdec_valatt",
}

for _npx_name, _op_name in _ALIASES.items():
    if _op_name is None:
        continue
    _schema = _registry.find_op(_op_name)
    if _schema is not None and not hasattr(_this, _npx_name):
        _f = make_op_func(_schema)
        _f.__name__ = _npx_name
        setattr(_this, _npx_name, _f)

# ops registered directly into the npx namespace (e.g. custom extensions
# via mx.library.register_op loaded before this module imported)
for _name, _schema in list(_registry._OPS.items()):
    if "npx" in _schema.namespaces and not hasattr(_this, _name):
        setattr(_this, _name, make_op_func(_schema))


def erf(x):
    import jax.scipy.special as jsp

    from ..numpy.multiarray import apply_np

    return apply_np(jsp.erf, "erf", (x,), {})


def erfinv(x):
    import jax.scipy.special as jsp

    from ..numpy.multiarray import apply_np

    return apply_np(jsp.erfinv, "erfinv", (x,), {})


def gelu(x, approximation="erf"):
    import jax.nn as jnn

    from ..numpy.multiarray import apply_np

    return apply_np(jnn.gelu, "gelu", (x,),
                    {"approximate": approximation != "erf"})


def reshape_like(lhs, rhs):
    from ..numpy.multiarray import apply_np
    import jax.numpy as jnp

    return apply_np(lambda a, b: jnp.reshape(a, b.shape), "reshape_like",
                    (lhs, rhs), {})


def waitall():
    from ..ndarray import waitall as _w

    _w()


def current_device():
    return current_context()


def index_update(x, ind, val):
    from ..ndarray.ndarray import _index_unwrap
    from ..numpy.multiarray import apply_np
    import jax.numpy as jnp

    ind = _index_unwrap(ind)
    if isinstance(ind, list):
        ind = jnp.asarray(ind)
    return apply_np(lambda a, v: a.at[ind].set(v), "index_update",
                    (x, val), {})


def index_add(x, ind, val):
    from ..ndarray.ndarray import _index_unwrap
    from ..numpy.multiarray import apply_np
    import jax.numpy as jnp

    ind = _index_unwrap(ind)
    if isinstance(ind, list):
        ind = jnp.asarray(ind)
    return apply_np(lambda a, v: a.at[ind].add(v), "index_add",
                    (x, val), {})


__all__ = sorted(
    [n for n in dir(_this) if not n.startswith("_")])
