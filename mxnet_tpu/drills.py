"""Deterministic end-to-end preemption/recovery drills.

ROADMAP item 4(c), executed: the fault framework (PR 2), the compiled
SPMD train step (PRs 3/6), the async engine (PR 5), the persistent
compile cache (PR 7), the generative engine (PR 8), and the preemption
subsystem (`mxnet_tpu/preemption.py`) have individually-tested recovery
paths — this module KILLS real processes running all of them at once
and measures what recovery actually costs (arXiv:2008.01040's
"measure, don't guess", applied to failure instead of throughput).

Every scenario is a scripted subprocess drill, fully deterministic — no
parent-side signal races: children trigger their own SIGTERM/SIGKILL at
a scripted step (a real ``os.kill`` to themselves, delivered through
the real installed handler), batches derive from the step index, and
greedy decode is token-exact, so a drill either reproduces bit-for-bit
or fails loudly:

- ``sigterm_drain`` — SIGTERM mid-step under the compiled SPMD
  ``TrainStep`` (4-device mesh) with the depth-k prefetcher and the
  async checkpoint writer running: the child drains, force-saves the
  last completed step, and exits with the distinguished code; the
  restarted child resumes with **0 steps replayed** and a loss
  trajectory bit-exact vs the uninterrupted reference.
- ``sigkill_between_saves`` — SIGKILL (no grace, no drain) between
  periodic saves: recovery restores the newest complete checkpoint,
  replays the gap deterministically (replayed losses bit-equal the
  first run's), leaves 0 temp-file litter.
- ``topology_change`` — checkpoint under a 4-device mesh, restart under
  a 2-device mesh: ``restore(like=)`` re-places bit-exactly (params
  digest match), the resumed 2-device trajectory is deterministic (two
  resumes bit-equal — run twice, the second proving warm-cache
  recovery performs 0 fresh compiles) and tracks the 4-device reference
  within float tolerance (cross-mesh reduction order differs by ulps;
  same-mesh drills assert bit-exact).
- ``corrupt_latest`` — flip one payload byte in the newest checkpoint
  (its sha256 sidecar now disagrees): restore degrades whole-step to
  the previous complete one, counted in ``checkpoint.digest_mismatches``,
  and the longer replay still lands bit-exact.
- ``decode_drain`` — SIGTERM mid-stream under the continuous-batching
  ``GenerativeEngine``: in-flight rows decode to completion (token-exact
  vs the eager oracle), queued requests come back as typed ``draining``
  sheds, 0 KV pages leak, and a second process serves the shed
  requests token-exactly.
- ``router_kill`` / ``router_wedge`` / ``router_flap`` /
  ``router_deadline_storm`` / ``router_prefix_storm``
  (``ROUTER_SCENARIOS``, gated by
  ``tools/check_availability_budget.py``) — the SERVING chaos matrix
  over a 2-replica ``serving_router.ReplicaRouter``: a replica killed
  mid-decode (its compiled programs start raising; every in-flight and
  queued request fails over, token-exact, 0 pages leaked, and a
  preemption notice afterwards still drains the router to the
  distinguished exit code), a wedged dispatch (hangs forever; the
  heartbeat wedge timeout evicts the replica inside
  ``MXNET_ROUTER_WEDGE_S``), a breaker flap (transient error burst
  opens the breaker; the half-open probe re-admits within the probe
  budget), a deadline storm (tight ``deadline_us`` budgets shed
  typed ``deadline`` within bounded wall clock — never a hang — while
  feasible budgets deliver token-exact), and a shared-prefix storm
  (ISSUE 16: every request shares one system prompt, so prefix
  affinity converges the fleet on the replica holding the warm
  hash-keyed pages — which is exactly the replica the drill then
  kills; failover rebuilds the cache cold on the survivor,
  token-exact, with the page-pool refcount audit clean at drain: 0
  leaked, 0 double-freed, no index entry pointing at a dead page).
- ``router_scale_storm`` / ``router_host_loss`` — the ISSUE-17 elastic
  fleet cells.  The scale storm runs a ``FleetSupervisor`` over a
  1-replica router under bursty load: the autoscaler grows the fleet
  1 → 3 by spawning cross-host ``replica`` children (each joins
  JOINING → warm → SERVING off the shared program cache: 0 fresh
  compiles) and, when the burst subsides, shrinks back 3 → 1 where
  every scale-down IS a scheduled graceful preemption (drain →
  ``preempt`` op → SIGTERM → typed draining sheds → exit 83).  Host
  loss SIGKILLs a remote replica's process mid-storm: every open call
  fails at once, failover redelivers token-exactly on the survivor,
  the breaker opens, and ``kill_to_recovered_s`` stays inside the
  availability wall.
- ``bitflip_param`` — the ISSUE-13 silent-corruption drill: the child
  flips one bit of ONE device's replica of a parameter mid-run; the
  sentinel's cross-replica digest vote localizes the device within one
  cadence (named in a ``corruption`` event, persisted to the
  quarantine list), rollback restores the last digest-verified
  checkpoint, the resumed trajectory is bit-exact vs the uninterrupted
  reference, and a restarted child re-resolves the mesh WITHOUT the
  quarantined device.
- ``loss_spike`` — scripted poisoned batch (targets scaled 1e6): the
  sentinel's grad-norm z-score window trips BEFORE the tainted state
  is checkpointed, rollback replays exactly the save-interval gap, and
  the merged trajectory is bit-exact vs the reference (the poison is
  one-shot, so the replay is clean).

``run_drill(name, root)`` orchestrates one scenario (children share
``<root>/pcache`` — the ``MXNET_PROGRAM_CACHE_DIR`` disk cache — and
the memoized reference run) and returns a report with the measured
**recovery-time budget**: ``recovery_s`` (checkpoint restore),
``recovery_wall_s`` (process start -> first resumed step),
``steps_replayed``, ``drain_s``, and the restart's disk
``fresh_compiles`` (0 when the cache is warm — the PR-7 promise).
``tools/check_recovery_budget.py`` gates all of it in CI; bench.py's
``elastic`` lane stamps the numbers into the artifact.

Child entry: ``python -m mxnet_tpu.drills train|decode ...`` (the
orchestrator builds the exact argv; children force ``JAX_PLATFORMS=cpu``
with an ``--xla_force_host_platform_device_count`` virtual mesh).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["SCENARIOS", "ROUTER_SCENARIOS", "run_drill", "main"]

SCENARIOS = ("sigterm_drain", "sigkill_between_saves", "topology_change",
             "corrupt_latest", "decode_drain", "bitflip_param",
             "loss_spike")
# the serving-availability matrix (tools/check_availability_budget.py);
# kept OUT of SCENARIOS so the recovery gate's matrix is unchanged
ROUTER_SCENARIOS = ("router_kill", "router_wedge", "router_flap",
                    "router_deadline_storm", "router_prefix_storm",
                    "router_scale_storm", "router_host_loss",
                    "spec_draft_poison")

# the scripted workload every train drill shares
N_STEPS = 24
SAVE_EVERY = 4
ROWS = 16
HALF = N_STEPS // 2
# cross-mesh tolerance: 4-dev vs 2-dev all-reduce order differs by ulps
# per step (same-mesh comparisons are bit-exact; see test_spmd_step's
# sharded-vs-single-chip contract)
TOPO_RTOL = 1e-4

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared child workload pieces (import mxnet_tpu lazily — the parent
# orchestrator must stay import-light)
# ---------------------------------------------------------------------------

def _host_batch(i: int):
    import numpy as onp

    rng = onp.random.RandomState(10_000 + int(i))
    return (rng.randn(ROWS, 8).astype(onp.float32),
            rng.randn(ROWS, 4).astype(onp.float32))


def _drill_net(seed: int = 0):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.d2 = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.d2(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(seed)
    for _name, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    return net


def _drill_loss(net, x, y):
    return ((net(x) - y) ** 2).mean()


def _warm_opt_states(trainer) -> None:
    """Create every updater state slot up front so the state tree's
    STRUCTURE is constant from step 0 (restore(like=) degrades to an
    older step on a structural mismatch — an empty-states initial
    capture would make every later checkpoint look unrestorable)."""
    opt = trainer._optimizer
    upd = trainer._updaters[0]
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        idx = trainer._param2idx[id(p)]
        if idx not in upd.states:
            upd.states[idx] = opt.create_state_multi_precision(
                idx, p.data())
            upd.states_synced[idx] = True
        opt._index_update_count.setdefault(idx, opt.begin_num_update)


def _capture(net, trainer):
    """Checkpointable pytree of everything the trajectory depends on:
    params, optimizer state (momentum buffers), and update counts."""
    import jax

    from mxnet_tpu.ndarray import NDArray

    def _leaf(x):
        return x._data if isinstance(x, NDArray) else x

    opt = trainer._optimizer
    states = {}
    for idx, s in trainer._updaters[0].states.items():
        states[int(idx)] = jax.tree_util.tree_map(_leaf, s)
    return {
        "params": {k: p.data()._data
                   for k, p in sorted(net.collect_params().items())},
        "opt": states,
        "counts": {int(i): int(c)
                   for i, c in opt._index_update_count.items()},
    }


def _restore_into(net, trainer, tree) -> None:
    """Push a restored :func:`_capture` tree back into the live net +
    trainer (the ``run_elastic(on_restore=)`` hookup): params keep
    their restored placement (``restore(like=)`` already re-placed them
    onto the CURRENT mesh), optimizer state re-wraps as NDArrays, and
    update counts catch up so schedules stay aligned."""
    import jax

    from mxnet_tpu.context import current_context
    from mxnet_tpu.ndarray.ndarray import _wrap

    for k, p in sorted(net.collect_params().items()):
        p.data()._set_data(tree["params"][k])
    upd = trainer._updaters[0]
    for idx, s in tree.get("opt", {}).items():
        upd.states[int(idx)] = jax.tree_util.tree_map(
            lambda x: _wrap(x, current_context()), s)
        upd.states_synced[int(idx)] = True
    opt = trainer._optimizer
    for i, c in tree.get("counts", {}).items():
        opt._index_update_count[int(i)] = int(c)
        opt.num_update = max(opt.num_update, int(c))


def _params_sha(net) -> str:
    import hashlib

    import numpy as onp

    h = hashlib.sha256()
    for k, p in sorted(net.collect_params().items()):
        h.update(k.encode())
        h.update(onp.ascontiguousarray(onp.asarray(p.data()._data)).tobytes())
    return h.hexdigest()


def _flip_param_bit(net, dev_index: int) -> int:
    """Silent-corruption injection: flip ONE mantissa bit of the first
    parameter's replica on mesh device position ``dev_index`` — the
    replicated array is rebuilt from per-device buffers with exactly
    one diverging, so only that physical replica carries the wrong
    bits (what a mis-executing chip or an HBM upset produces).
    Returns the id of the corrupted device."""
    import jax
    import numpy as onp

    _name, p = sorted(net.collect_params().items())[0]
    arr = p.data()._data
    shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    bufs, victim = [], None
    for j, sh in enumerate(shards):
        host = onp.asarray(sh.data).copy()
        if j == dev_index % len(shards):
            victim = sh.device.id
            host.view(onp.uint32).ravel()[3] ^= onp.uint32(1 << 20)
        bufs.append(jax.device_put(host, sh.device))
    p.data()._set_data(jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, bufs))
    return victim


# ---------------------------------------------------------------------------
# child: train drill
# ---------------------------------------------------------------------------

def _cmd_train(a) -> int:
    t_proc0 = time.monotonic()
    import mxnet_tpu as mx  # noqa: F401  (installs the runtime)
    from mxnet_tpu import engine, gluon, preemption, program_store, telemetry
    from mxnet_tpu.parallel.elastic import CheckpointManager, run_elastic

    net = _drill_net(seed=0)
    trainer = gluon.Trainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, kvstore="tpu")
    step = trainer.compile_step(net, _drill_loss)
    _warm_opt_states(trainer)
    ckpt = CheckpointManager(a.ckpt, keep=20, async_save=True)
    snt = None
    if a.sentinel_every:
        # constructed BEFORE the mesh resolves: a quarantine list
        # persisted by a prior incarnation excludes its suspects from
        # this process's mesh (the restart-time consumption contract)
        from mxnet_tpu import sentinel as _sentinel

        snt = _sentinel.Sentinel(step=step, directory=a.ckpt,
                                 every=a.sentinel_every)
    if a.preempt:
        preemption.install()
    losses_f = open(os.path.join(a.dir, f"losses-{a.label}.txt"), "a",
                    buffering=1)
    progress_f = open(os.path.join(a.dir, f"progress-{a.label}.txt"), "a",
                      buffering=1)

    # one-shot scripted events: after a rollback the replay regenerates
    # the SAME step indices, and a re-fired poison/flip would make the
    # drill diverge forever instead of proving bit-exact recovery
    fired = {"poison": False, "flip": False}

    def _drill_batch(j: int):
        x, y = _host_batch(j)
        if a.poison_at is not None and j == a.poison_at \
                and not fired["poison"]:
            fired["poison"] = True
            y = (y * 1e6).astype(y.dtype)
        return x, y

    # depth-k prefetcher staging batches onto the step's mesh sharding;
    # restarted from the restored index after every restore (the input
    # pipeline is part of what restore-and-replay rebuilds)
    pf = {"it": None, "next": -1}

    def _get_batch(i: int):
        if pf["it"] is None or pf["next"] != i:
            if hasattr(pf["it"], "close"):
                pf["it"].close()
            pf["it"] = engine.prefetch(
                (_drill_batch(j) for j in range(i, a.stop_at)),
                depth=2, sharding=step.batch_sharding)
            pf["next"] = i
        pf["next"] = i + 1
        return next(iter(pf["it"]))

    t_first = [None]
    restored_at = [None]
    restored_sha = [None]
    flipped_dev = [None]

    def step_fn(state, i):
        if a.sigkill_at is not None and i == a.sigkill_at:
            # let the queued async saves land first so the drill's
            # restore point is deterministic — the kill still falls
            # BETWEEN save boundaries (i % save_every != 0)
            ckpt.wait()
            os.kill(os.getpid(), signal.SIGKILL)      # no grace, no drain
        if a.sigterm_at is not None and i == a.sigterm_at \
                and restored_at[0] is None:
            # a real preemption notice, delivered mid-step through the
            # installed handler (the handler runs at the next bytecode)
            os.kill(os.getpid(), signal.SIGTERM)
        if a.bitflip_at is not None and i == a.bitflip_at \
                and not fired["flip"]:
            fired["flip"] = True
            flipped_dev[0] = _flip_param_bit(net, a.bitflip_dev)
        x, y = _get_batch(i)
        loss = step(x, y, batch_size=ROWS)
        lval = float(loss.asnumpy().ravel()[0])
        losses_f.write(f"{i} {lval.hex()}\n")
        progress_f.write(f"{i}\n")
        if t_first[0] is None:
            t_first[0] = time.monotonic()
        if a.delay:
            time.sleep(a.delay)
        return _capture(net, trainer)

    def on_restore(state, s):
        restored_at[0] = s
        _restore_into(net, trainer, state)
        restored_sha[0] = _params_sha(net)   # proves restore == saved
        pf["next"] = -1                 # restart the input pipeline
        return None

    preempted: Optional[int] = None
    steps_run = restarts = None
    try:
        _out, steps_run, restarts = run_elastic(
            step_fn, _capture(net, trainer), range(a.stop_at), ckpt,
            save_every=a.save_every, max_restarts=a.max_restarts,
            on_restore=on_restore, anomaly_fn=snt)
    except preemption.Preempted as e:
        preempted = int(e.code)
    engine.waitall()
    snap = telemetry.snapshot()
    telemetry.flush()       # shard == the snapshot this result records
    mesh = step.mesh
    res = {
        "label": a.label, "pid": os.getpid(),
        "preempted_code": preempted,
        "steps_run": steps_run, "restarts": restarts,
        "restored_at": restored_at[0],
        "restored_params_sha": restored_sha[0],
        "params_sha": _params_sha(net),
        "disk": program_store.disk_stats(),
        "recovery_s": snap.get("elastic.recovery_s"),
        "steps_replayed": snap.get("elastic.steps_replayed"),
        "drain_s": snap.get("preemption.drain_s"),
        "digest_mismatches": snap.get("checkpoint.digest_mismatches"),
        "wall_s": time.monotonic() - t_proc0,
        "first_step_s": (t_first[0] - t_proc0
                         if t_first[0] is not None else None),
        "mesh_devices": ([int(d.id) for d in mesh.devices.flat]
                         if mesh is not None else None),
        "flipped_device": flipped_dev[0],
        "sentinel_digests": snap.get("sentinel.digests"),
        "replica_divergence": snap.get("sentinel.replica_divergence"),
        "rollbacks": snap.get("sentinel.rollbacks"),
        "last_rollback": snt.last_rollback if snt is not None else None,
        "quarantine": (snt.quarantine.entries()
                       if snt is not None else None),
        "corruption_events": telemetry.events(kind="corruption"),
        "telemetry": snap,
    }
    with open(os.path.join(a.dir, f"result-{a.label}.json"), "w") as f:
        json.dump(res, f)
    return preempted or 0


# ---------------------------------------------------------------------------
# child: decode drill
# ---------------------------------------------------------------------------

def _decode_prompt(r: int) -> List[int]:
    return [1 + (r * 7 + j) % 49 for j in range(5 + r % 3)]


def _cmd_decode(a) -> int:
    import threading

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import engine, preemption, telemetry
    from mxnet_tpu.faults import ShedError
    from mxnet_tpu.serving_decode import (GenerativeEngine, PagePool,
                                          TinyCausalLM, eager_generate)

    model = TinyCausalLM(vocab=50, d_model=16, n_layers=1, n_heads=2,
                        max_seq=96)
    params = model.init_params(0)
    pool = PagePool(pages=64, page=8)
    eng = GenerativeEngine(model, params=params, pool=pool, max_rows=2,
                           name="drill")
    eng.warmup(max_len=8)
    if a.preempt:
        preemption.install()
    req_ids = [int(r) for r in a.requests.split(",") if r != ""]
    delivered: Dict[int, List[int]] = {}
    shed: Dict[int, Optional[str]] = {}
    trigger = {"fired": False}
    lock = threading.Lock()

    def worker(r: int):
        try:
            toks = eng.generate(_decode_prompt(r),
                                max_new_tokens=a.max_new)
            with lock:
                delivered[r] = [int(t) for t in toks]
        except ShedError as e:
            with lock:
                shed[r] = getattr(e, "kind", None)
        except BaseException as e:          # pragma: no cover - drill fail
            with lock:
                shed[r] = f"error:{e!r}"
        with lock:
            fire = (a.self_sigterm and not trigger["fired"]
                    and len(delivered) >= 1)
            trigger["fired"] = trigger["fired"] or fire
        if fire:
            # deterministic mid-stream preemption: the FIRST delivery
            # proves decode is rolling, other rows are live, the queue
            # is non-empty — notice now (delivered to the main thread)
            os.kill(os.getpid(), signal.SIGTERM)

    # graftlint: daemon-ok(drill request workers, joined in-scope below
    # before the drill writes its verdict)
    threads = [threading.Thread(target=worker, args=(r,)) for r in req_ids]
    for t in threads:
        t.start()
    preempted: Optional[int] = None
    try:
        for t in threads:
            while t.is_alive():
                t.join(timeout=0.05)     # interruptible by the handler
    except preemption.Preempted as e:
        preempted = int(e.code)
        for t in threads:
            t.join(timeout=30.0)        # drain already completed them
    engine.waitall()
    # token-exact vs the eager oracle on a deterministic subset (the
    # oracle re-runs a FULL eager forward per token — verifying every
    # delivery would dominate the drill's wall clock)
    verify = sorted(delivered)[:2]
    token_exact = all(
        delivered[r] == eager_generate(model, params, _decode_prompt(r),
                                       a.max_new)
        for r in verify)
    snap = telemetry.snapshot()
    telemetry.flush()       # shard == the snapshot this result records
    res = {
        "label": a.label, "preempted_code": preempted,
        "delivered": {str(r): t for r, t in delivered.items()},
        "shed": {str(r): k for r, k in shed.items()},
        "token_exact": token_exact,
        "pool_in_use": pool.in_use(),
        "drain_s": snap.get("preemption.drain_s"),
        "telemetry": snap,
    }
    with open(os.path.join(a.dir, f"result-{a.label}.json"), "w") as f:
        json.dump(res, f)
    return preempted or 0


# ---------------------------------------------------------------------------
# child: router chaos drill (the serving-availability matrix)
# ---------------------------------------------------------------------------

def _router_prompt(r: int) -> List[int]:
    return [1 + (r * 5 + j) % 47 for j in range(4 + r % 4)]


# the prefix-storm system prompt: 3 full page-blocks (page=8) every
# storm request shares, so the fleet's prefill work should scale with
# UNIQUE suffix bytes, not request count
_STORM_SYS = [2 + (j * 11) % 43 for j in range(24)]


def _storm_prompt(r: int) -> List[int]:
    # every 3rd request is byte-identical (full hit); the rest diverge
    # after the shared system prompt (partial hit + COW fork)
    if r % 3 == 0:
        return list(_STORM_SYS)
    return _STORM_SYS + [5 + (r * 7 + j) % 41 for j in range(2 + r % 3)]


def _cmd_router(a) -> int:
    if a.mode in ("scale_storm", "host_loss"):
        return _cmd_router_fleet(a)
    import threading

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import engine, faults, preemption, telemetry
    from mxnet_tpu.faults import ShedError
    from mxnet_tpu.serving_decode import (GenerativeEngine, PagePool,
                                          TinyCausalLM, eager_generate,
                                          high_agreement_pair)
    from mxnet_tpu.serving_router import ReplicaRouter

    spec_kw: Dict[str, Any] = {}
    if a.mode == "spec_draft_poison":
        # ISSUE 19: the speculative cell runs a HIGH-agreement pair so
        # the steady phase demonstrably engages speculation before the
        # draft is poisoned (the knob is uncached; child-local flip)
        os.environ["MXNET_SPEC_DECODE"] = "1"
        model, params, draft, dparams = high_agreement_pair(
            vocab=50, d_model=16, target_layers=2, draft_layers=1,
            n_heads=2, max_seq=96)
        spec_kw = dict(draft=draft, draft_params=dparams, spec_k=4)
    else:
        model = TinyCausalLM(vocab=50, d_model=16, n_layers=1,
                             n_heads=2, max_seq=96)
        params = model.init_params(0)
    pools = [PagePool(pages=64, page=8), PagePool(pages=64, page=8)]
    engines = [GenerativeEngine(model, params=params, pool=pools[i],
                                max_rows=2, name=f"rep{i}", **spec_kw)
               for i in range(2)]
    for e in engines:
        e.warmup(max_len=8)
    router = ReplicaRouter(
        engines, name="drill", breaker_errs=2, breaker_cooldown_s=0.5,
        wedge_s=(1.5 if a.mode == "wedge" else 30.0), hedge_pctl=0)
    if a.preempt:
        preemption.install()
    # the prefix storm routes every request through ONE shared system
    # prompt; the other modes keep their fully distinct prompts
    prompt_of = (_storm_prompt if a.mode == "prefix_storm"
                 else _router_prompt)

    records: Dict[int, Dict[str, Any]] = {}
    lock = threading.Lock()

    def fire(rid: int, deadline_us: Optional[int] = None) -> None:
        t0 = time.monotonic()
        rec: Dict[str, Any] = {
            "budget_s": deadline_us / 1e6 if deadline_us else None}
        try:
            toks = router.generate(prompt_of(rid),
                                   max_new_tokens=a.max_new,
                                   deadline_us=deadline_us)
            rec.update(status="delivered",
                       tokens=[int(t) for t in toks])
        except ShedError as e:
            rec.update(status="shed", kind=getattr(e, "kind", None))
        except BaseException as e:   # pragma: no cover - drill failure
            rec.update(status="error", error=repr(e))
        rec["elapsed_s"] = time.monotonic() - t0
        with lock:
            records[rid] = rec

    # -- phase A: steady state (sequential; also warms the cost table) --
    for rid in range(a.steady):
        fire(rid)
    steady_lat = sorted(records[r]["elapsed_s"] for r in range(a.steady)
                        if records[r]["status"] == "delivered")
    steady_p99_s = (steady_lat[min(len(steady_lat) - 1,
                                   int(len(steady_lat) * 0.99))]
                    if steady_lat else None)

    # -- chaos injection -------------------------------------------------
    orig_gen = engines[0].generate
    flap_calls = {"n": 0}

    class _Boom:
        """Stand-in for replica 0's compiled programs after the 'kill':
        the scheduler's next decode/prefill lookup raises — exactly what
        an engine whose process segment died mid-decode looks like from
        the host thread."""

        def __call__(self, *args, **kw):
            raise RuntimeError("replica 0 killed mid-decode")

    def apply_chaos() -> None:
        if a.mode in ("kill", "prefix_storm"):
            boom = _Boom()
            engines[0]._programs.insert(("decode",), boom)
            for b in (1, 2, 4, 8, 16, 32):
                engines[0]._programs.insert(("prefill", b), boom)
                engines[0]._programs.insert(("prefill_chunk", b), boom)
        elif a.mode == "wedge":
            def wedged(*args, **kw):
                time.sleep(120.0)
                raise RuntimeError("wedged dispatch finally released")
            engines[0].generate = wedged
        elif a.mode == "flap":
            def flaky(*args, **kw):
                flap_calls["n"] += 1
                if flap_calls["n"] <= 4:
                    raise faults.TransientFault(
                        f"flap {flap_calls['n']}")
                return orig_gen(*args, **kw)
            engines[0].generate = flaky
        elif a.mode == "spec_draft_poison":
            # wedge BOTH replicas' draft-round programs: every next
            # spec round raises, the engines must auto-disable via the
            # cost-table path and degrade to plain decode in-place —
            # no failover, no drop, token streams unchanged
            def poisoned(*args, **kw):
                raise RuntimeError("draft model poisoned mid-round")
            for e in engines:
                e._spec_programs.insert(("draft_round", 4), poisoned)

    # -- phase B: chaos under concurrent load ---------------------------
    base = a.steady
    chaos_ids = list(range(base, base + a.requests))
    if a.mode == "deadline_storm":
        # alternating infeasible (3 ms — the cost table prices a
        # max_new-token request far above it) and feasible budgets
        budgets = {rid: (3_000 if i % 2 == 0 else 30_000_000)
                   for i, rid in enumerate(chaos_ids)}
    else:
        budgets = {rid: None for rid in chaos_ids}
    # graftlint: daemon-ok(drill request workers, joined in-scope below
    # before the drill writes its verdict)
    threads = [threading.Thread(target=fire, args=(rid, budgets[rid]))
               for rid in chaos_ids]
    for t in threads:
        t.start()
    if a.mode in ("kill", "prefix_storm"):
        # strike while replica 0 is actively decoding chaos rows: wait
        # for its decode counter to move with live rows (bounded poll).
        # In the prefix storm replica 0 is ALSO the affinity target —
        # it took the first steady request, published the shared
        # prompt, and pulled the whole storm onto its warm pages — so
        # this kill lands on the cache itself.
        d0 = engines[0]._stats["decode_steps"]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if engines[0]._stats["decode_steps"] > d0 and \
                    len(engines[0]._live) > 0:
                break
            time.sleep(0.001)
        apply_chaos()
    elif a.mode in ("wedge", "flap", "spec_draft_poison"):
        apply_chaos()
    for t in threads:
        t.join(timeout=180.0)

    # -- flap: measure breaker re-admission (probe budget) --------------
    re_admit_s = None
    if a.mode == "flap":
        t0 = time.monotonic()
        deadline = t0 + 10.0
        while time.monotonic() < deadline:
            if router.breaker_state(0) == "closed":
                re_admit_s = time.monotonic() - t0
                break
            fire(10_000 + int((time.monotonic() - t0) * 1000))
            time.sleep(0.05)

    # -- kill: the PR-11 preemption leg — the router must still drain ---
    preempted: Optional[int] = None
    drain_ids: List[int] = []
    if a.preempt:
        drain_ids = list(range(20_000, 20_000 + 4))
        fired = {"sig": False}

        def drain_worker(rid: int) -> None:
            fire(rid)
            with lock:
                fire_now = not fired["sig"] and any(
                    records.get(r, {}).get("status") == "delivered"
                    for r in drain_ids if r in records)
                fired["sig"] = fired["sig"] or fire_now
            if fire_now:
                os.kill(os.getpid(), signal.SIGTERM)

        # graftlint: daemon-ok(drill request workers, joined in-scope
        # below before the drill writes its verdict)
        dthreads = [threading.Thread(target=drain_worker, args=(rid,))
                    for rid in drain_ids]
        for t in dthreads:
            t.start()
        try:
            for t in dthreads:
                while t.is_alive():
                    t.join(timeout=0.05)
        except preemption.Preempted as e:
            preempted = int(e.code)
            for t in dthreads:
                t.join(timeout=30.0)
    engine.waitall()

    # token-exactness of every delivered response vs the eager oracle
    # (the drill's model is tiny, so full verification is affordable)
    token_exact = True
    oracle_cache: Dict[int, List[int]] = {}
    for rid, rec in sorted(records.items()):
        if rec["status"] != "delivered":
            continue
        if rid not in oracle_cache:
            oracle_cache[rid] = eager_generate(
                model, params, prompt_of(rid), a.max_new)
        if rec["tokens"] != oracle_cache[rid]:
            token_exact = False
            rec["oracle"] = oracle_cache[rid]

    st = router.stats()
    # ISSUE-16 refcount audit at drain: every page accounted for
    # exactly once (free, cached, or referenced), no index entry
    # pointing at a dead page — 0 leaked AND 0 double-freed
    pool_audit = [m for p in pools for m in p.audit()]
    snap = telemetry.snapshot()
    hit_blocks = int(snap.get("prefix.hit_blocks", 0))
    miss_blocks = int(snap.get("prefix.miss_blocks", 0))
    telemetry.flush()       # shard == the snapshot this result records
    res = {
        "label": a.label, "mode": a.mode, "pid": os.getpid(),
        "preempted_code": preempted,
        "steady_ids": list(range(a.steady)),
        "chaos_ids": chaos_ids,
        "drain_ids": drain_ids,
        "records": {str(k): v for k, v in records.items()},
        "token_exact": token_exact,
        "steady_p99_s": steady_p99_s,
        "re_admit_s": re_admit_s,
        "leaked_pages": sum(p.in_use() for p in pools),
        "pool_audit": pool_audit,
        "prefix_hit_blocks": hit_blocks,
        "prefix_miss_blocks": miss_blocks,
        "prefix_cow_forks": int(snap.get("prefix.cow_forks", 0)),
        "prefix_hit_rate": hit_blocks / max(hit_blocks + miss_blocks, 1),
        "router": {k: v for k, v in st.items() if k != "replicas"},
        "breakers": [r["breaker"] for r in st["replicas"]],
        "spec": [{k: e.stats()[k]
                  for k in ("spec_rounds", "spec_proposed",
                            "spec_accepted", "spec_fallbacks",
                            "spec_disabled")}
                 for e in engines] if spec_kw else None,
        "drain_s": telemetry.snapshot().get("preemption.drain_s"),
        "telemetry": telemetry.snapshot(),
    }
    with open(os.path.join(a.dir, f"result-{a.label}.json"), "w") as f:
        json.dump(res, f)
    return preempted or 0


# ---------------------------------------------------------------------------
# child: one cross-host replica process (ISSUE 17 — the elastic fleet's
# unit of membership)
# ---------------------------------------------------------------------------

def _cmd_replica(a) -> int:
    """Warm a ``GenerativeEngine`` off the shared program cache, serve
    it over ``serving_remote.ReplicaServer``, and wait for retirement:
    a graceful preemption (the router's ``preempt`` op → SIGTERM →
    typed draining sheds → waitall → result JSON → exit 83) or a
    SIGKILL (the host-loss cell: no goodbye at all)."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import engine, preemption, program_store, telemetry
    from mxnet_tpu.serving_decode import (GenerativeEngine, PagePool,
                                          TinyCausalLM)
    from mxnet_tpu.serving_remote import ReplicaServer

    model = TinyCausalLM(vocab=50, d_model=16, n_layers=1, n_heads=2,
                         max_seq=96)
    params = model.init_params(0)
    pool = PagePool(pages=64, page=8)
    eng = GenerativeEngine(model, params=params, pool=pool, max_rows=2,
                           name=a.label)
    eng.warmup(max_len=8)       # off <root>/pcache: disk hits only
    preemption.install()
    srv = ReplicaServer(eng, name=a.label).start()
    # the port file is the join handshake, written AFTER warmup — the
    # supervisor's join clock prices the WHOLE boot tax
    tmp = os.path.join(a.dir, f"port-{a.label}.tmp")
    with open(tmp, "w") as f:
        f.write(f"{srv.port}\n")
    os.replace(tmp, os.path.join(a.dir, f"port-{a.label}.txt"))
    t0 = time.monotonic()
    preempted: Optional[int] = None
    try:
        while time.monotonic() - t0 < a.ttl:   # orphan guard
            time.sleep(0.1)
    except preemption.Preempted as e:
        preempted = int(e.code)
    engine.waitall()
    snap = telemetry.snapshot()
    telemetry.flush()       # shard == the snapshot this result records
    res = {
        "label": a.label, "pid": os.getpid(),
        "preempted_code": preempted,
        "disk": program_store.disk_stats(),
        "leaked_pages": pool.in_use(),
        "pool_audit": list(pool.audit()),
        "served": {k: v for k, v in eng.stats().items()
                   if isinstance(v, (int, float))},
        "drain_s": snap.get("preemption.drain_s"),
        "telemetry": snap,
    }
    with open(os.path.join(a.dir, f"result-{a.label}.json"), "w") as f:
        json.dump(res, f)
    return preempted or 0


def _spawn_replica(scen_dir: str, label: str, boot_timeout: float = 120.0
                   ) -> "tuple[subprocess.Popen, int]":
    """Launch a ``replica`` child and wait for its port handshake.
    Returns ``(popen, port)``; the caller owns the process handle.
    Environment is inherited — the fleet shares ``MXNET_PROGRAM_CACHE_DIR``
    (warm joins) and ``MXNET_TELEMETRY_DIR`` (rank-stamped shards)."""
    port_path = os.path.join(scen_dir, f"port-{label}.txt")
    if os.path.exists(port_path):
        os.remove(port_path)
    log = open(os.path.join(scen_dir, f"replica-{label}.log"), "w")
    popen = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.drills", "replica",
         "--dir", scen_dir, "--label", label],
        stdout=log, stderr=subprocess.STDOUT, cwd=_REPO)
    deadline = time.monotonic() + boot_timeout
    while time.monotonic() < deadline:
        if os.path.exists(port_path):
            with open(port_path) as f:
                return popen, int(f.read().strip())
        if popen.poll() is not None:
            raise RuntimeError(f"replica {label} died during boot "
                               f"rc={popen.returncode}")
        time.sleep(0.05)
    popen.kill()
    raise RuntimeError(f"replica {label} never published its port")


def _cmd_router_fleet(a) -> int:
    """The ISSUE-17 elastic-fleet cells.

    ``scale_storm``: a ``FleetSupervisor`` over a 1-replica router under
    bursty load — the autoscaler grows 1 → 3 by spawning ``replica``
    children (JOINING → warm → SERVING, 0 fresh compiles off the shared
    cache), one remote is gracefully preempted WHILE serving (typed
    draining sheds hand queued rows back over the wire), and the
    subsiding burst shrinks the fleet back to 1 where every scale-down
    IS a scheduled graceful preemption (drain → SIGTERM → exit 83).

    ``host_loss``: a 2-replica router (local + remote) has the remote's
    process SIGKILLed mid-storm — every open call fails at once,
    failover redelivers token-exactly on the survivor, the breaker
    opens, and ``kill_to_recovered_s`` is measured for the gate."""
    import threading

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import engine, telemetry
    from mxnet_tpu.faults import ShedError
    from mxnet_tpu.serving_decode import (GenerativeEngine, PagePool,
                                          TinyCausalLM, eager_generate)
    from mxnet_tpu.serving_remote import RemoteReplica
    from mxnet_tpu.serving_router import (FleetSupervisor, ReplicaRouter,
                                          REPLICA_SERVING)

    model = TinyCausalLM(vocab=50, d_model=16, n_layers=1, n_heads=2,
                         max_seq=96)
    params = model.init_params(0)
    pool0 = PagePool(pages=64, page=8)
    local = GenerativeEngine(model, params=params, pool=pool0,
                             max_rows=2, name="rep0")
    # warms <root>/pcache BEFORE any replica spawns: joiners hit disk
    local.warmup(max_len=8)

    def prompt_of(rid: int) -> List[int]:
        # bounded distinct prompts: the eager oracle replays each
        # UNIQUE prompt, so the storm cycles 29 instead of minting
        # hundreds
        return _router_prompt(rid % 29)

    records: Dict[int, Dict[str, Any]] = {}
    lock = threading.Lock()

    def fire(rid: int) -> None:
        t0 = time.monotonic()
        rec: Dict[str, Any] = {}
        try:
            toks = router.generate(prompt_of(rid),
                                   max_new_tokens=a.max_new)
            rec.update(status="delivered",
                       tokens=[int(t) for t in toks])
        except ShedError as e:
            rec.update(status="shed", kind=getattr(e, "kind", None))
        except BaseException as e:   # pragma: no cover - drill failure
            rec.update(status="error", error=repr(e))
        rec["elapsed_s"] = time.monotonic() - t0
        rec["done_at"] = time.monotonic()
        with lock:
            records[rid] = rec

    extra: Dict[str, Any] = {}
    procs: List[Dict[str, Any]] = []

    if a.mode == "host_loss":
        popen, port = _spawn_replica(a.dir, "r1")
        procs.append({"label": "r1", "popen": popen})
        remote = RemoteReplica("127.0.0.1", port, name="r1")
        router = ReplicaRouter([local, remote], name="drill",
                               breaker_errs=2, breaker_cooldown_s=0.5,
                               hedge_pctl=0)
        for rid in range(a.steady):
            fire(rid)
        base = a.steady
        chaos_ids = list(range(base, base + max(a.requests, 10)))
        # graftlint: daemon-ok(drill request workers, joined in-scope
        # below before the drill writes its verdict)
        threads = [threading.Thread(target=fire, args=(rid,))
                   for rid in chaos_ids]
        for t in threads:
            t.start()
        # strike while the remote is actively serving: the router's own
        # in-flight ledger for replica 1, no wire round trip
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router._replicas[1].in_flight > 0:
                break
            time.sleep(0.002)
        os.kill(popen.pid, signal.SIGKILL)
        t_kill = time.monotonic()
        popen.wait(timeout=30)
        for t in threads:
            t.join(timeout=180.0)
        # recovery = first delivery COMPLETED after the kill: the fleet
        # is answering again (failover absorbed the loss)
        with lock:
            done_after = sorted(
                v["done_at"] - t_kill for v in records.values()
                if v["status"] == "delivered" and v["done_at"] > t_kill)
        extra["kill_to_recovered_s"] = (done_after[0] if done_after
                                        else None)
        # open the corpse's breaker deterministically: concurrent fires
        # (a lone sequential request always picks the idle local
        # replica and the corpse would never be touched again)
        t0p = time.monotonic()
        rid = 30_000
        while (router.breaker_state(1) == "closed"
               and time.monotonic() - t0p < 15.0):
            # graftlint: daemon-ok(drill request workers, joined on the
            # next line)
            burst = [threading.Thread(target=fire, args=(rid + k,))
                     for k in range(4)]
            rid += 4
            for t in burst:
                t.start()
            for t in burst:
                t.join(timeout=60.0)
        chaos_ids = sorted(r for r in records if r >= base)
        remote.close()
        extra["remote_rc"] = popen.returncode

    else:   # scale_storm
        router = ReplicaRouter([local], name="drill", breaker_errs=2,
                               breaker_cooldown_s=0.5, hedge_pctl=0)
        plock = threading.Lock()

        def spawn():
            with plock:
                ent: Dict[str, Any] = {
                    "label": f"r{len(procs) + 1}",
                    "t_spawn": time.monotonic(),
                    "first_served_s": None, "exit_code": None}
                procs.append(ent)
            popen, port = _spawn_replica(a.dir, ent["label"])
            ent["popen"] = popen
            rr = RemoteReplica("127.0.0.1", port, name=ent["label"])
            ent["rr"] = rr
            return rr

        def retire(eng_, index: int) -> None:
            ent = next((e for e in procs if e.get("rr") is eng_), None)
            try:
                eng_.preempt()
            except BaseException:
                pass        # already dead (the preempt-under-load leg)
            if ent is not None and ent.get("popen") is not None:
                try:
                    ent["exit_code"] = ent["popen"].wait(timeout=60)
                except subprocess.TimeoutExpired:
                    ent["popen"].kill()
                    ent["exit_code"] = -9
            eng_.close()

        sup = FleetSupervisor(router, spawn, retire=retire, enabled=True,
                              min_replicas=1, max_replicas=3,
                              cooldown_s=0.3, interval_s=0.05,
                              up_queue=0.75, down_queue=0.05,
                              pool_high=0.95,
                              warmup_kwargs={"max_len": 8})
        sup.start()
        for rid in range(a.steady):
            fire(rid)
        base = a.steady
        # -- the burst: keep ~12 requests in flight until the fleet
        # reaches 3 SERVING replicas and each joiner took traffic ------
        threads: List[threading.Thread] = []
        next_rid = base
        storm_deadline = time.monotonic() + 240.0
        while time.monotonic() < storm_deadline:
            threads = [t for t in threads if t.is_alive()]
            while len(threads) < 12:
                # graftlint: daemon-ok(drill request workers, joined
                # in-scope below before the drill writes its verdict)
                t = threading.Thread(target=fire, args=(next_rid,))
                next_rid += 1
                t.start()
                threads.append(t)
            for r in list(router._replicas):
                if r.index == 0 or r.state != REPLICA_SERVING:
                    continue
                ent = next((e for e in procs
                            if e.get("rr") is r.engine), None)
                if (ent is not None and ent["first_served_s"] is None
                        and r.in_flight > 0):
                    ent["first_served_s"] = round(
                        time.monotonic() - ent["t_spawn"], 3)
            if (router.fleet_stats()["scale_ups"] >= 2
                    and all(e["first_served_s"] is not None
                            for e in procs if e.get("rr"))):
                break
            time.sleep(0.01)
        # -- graceful preemption UNDER LOAD: SIGTERM the youngest remote
        # while rows are queued on it — the queued rows come back as
        # typed draining sheds over the wire and fail over token-exact
        victims = [e for e in procs if e.get("rr") is not None]
        queued_at_preempt = 0
        if victims:
            ent = victims[-1]
            vr = next((r for r in list(router._replicas)
                       if r.engine is ent["rr"]), None)
            deadline = time.monotonic() + 10.0
            while (vr is not None and time.monotonic() < deadline
                   and vr.in_flight < 3):
                time.sleep(0.002)
            queued_at_preempt = vr.in_flight if vr is not None else 0
            try:
                ent["rr"].preempt()
                ent["exit_code"] = ent["popen"].wait(timeout=60)
            except BaseException as e:
                extra["preempt_error"] = repr(e)
        extra["queued_at_preempt"] = queued_at_preempt
        for t in threads:
            t.join(timeout=300.0)
        chaos_ids = list(range(base, next_rid))
        # -- the burst subsided: the supervisor shrinks back to 1, each
        # scale-down a drain → preempt → exit-83 retirement ------------
        down_deadline = time.monotonic() + 120.0
        while time.monotonic() < down_deadline:
            if (router.serving_replicas() == 1
                    and all(e.get("exit_code") is not None
                            for e in procs if e.get("popen"))):
                break
            time.sleep(0.05)
        sup.stop()
        for e in procs:
            e.pop("rr", None)
            e.pop("popen", None)
            e.pop("t_spawn", None)

    engine.waitall()

    # token-exactness of every delivered response vs the eager oracle
    token_exact = True
    oracle_cache: Dict[str, List[int]] = {}
    for rid, rec in sorted(records.items()):
        if rec["status"] != "delivered":
            continue
        key = str(prompt_of(rid))
        if key not in oracle_cache:
            oracle_cache[key] = eager_generate(
                model, params, prompt_of(rid), a.max_new)
        if rec["tokens"] != oracle_cache[key]:
            token_exact = False
            rec["oracle"] = oracle_cache[key]

    st = router.stats()
    remotes = []
    for e in procs:
        rres = _read_result(a.dir, e["label"]) or {}
        remotes.append({
            "label": e["label"],
            "exit_code": e.get("exit_code"),
            "first_served_s": e.get("first_served_s"),
            "preempted_code": rres.get("preempted_code"),
            "fresh_compiles": (rres.get("disk") or {}).get("misses"),
            "disk_hits": (rres.get("disk") or {}).get("hits"),
            "leaked_pages": rres.get("leaked_pages"),
            "pool_audit": rres.get("pool_audit"),
            "shed_draining": (rres.get("served") or {}).get(
                "shed_draining"),
        })
    telemetry.flush()       # shard == the snapshot this result records
    res = {
        "label": a.label, "mode": a.mode, "pid": os.getpid(),
        "preempted_code": None,
        "steady_ids": list(range(a.steady)),
        "chaos_ids": chaos_ids,
        "drain_ids": [],
        "records": {str(k): v for k, v in records.items()},
        "token_exact": token_exact,
        "steady_p99_s": None,
        "leaked_pages": pool0.in_use(),
        "pool_audit": [m for m in pool0.audit()],
        "router": {k: v for k, v in st.items() if k != "replicas"},
        "replica_states": [r["state"] for r in st["replicas"]],
        "breakers": [r["breaker"] for r in st["replicas"]],
        "remotes": remotes,
        "telemetry": telemetry.snapshot(),
        **extra,
    }
    with open(os.path.join(a.dir, f"result-{a.label}.json"), "w") as f:
        json.dump(res, f)
    return 0


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _drill_telemetry_dir(root: str) -> str:
    """Where this drill's child processes flush their flight-recorder
    shards (ISSUE 15): an outer ``MXNET_TELEMETRY_DIR`` (bench.py's
    fleet dir) wins so the bench lane's merge sees drill children too;
    otherwise a per-root directory the parent merges for its
    merged-vs-observed assertions."""
    from mxnet_tpu import config as _config

    return _config.get("MXNET_TELEMETRY_DIR") \
        or os.path.join(root, "telemetry")


def _child_env(root: str, devices: int) -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices}")
    env["MXNET_SPMD_MESH"] = "auto"
    env["MXNET_PROGRAM_CACHE_DIR"] = os.path.join(root, "pcache")
    env["MXNET_PREEMPTION_GRACE_S"] = "60"
    env["MXNET_ENGINE_PREFETCH"] = "2"
    env["MXNET_RETRY_BACKOFF"] = "0.01"
    env["MXNET_ELASTIC_BACKOFF"] = "0"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in ("MXNET_FAULT_PLAN", "MXNET_ENGINE_TYPE",
              "JAX_COMPILATION_CACHE_DIR"):
        env.pop(k, None)
    # children are fleet members: each flushes an atomic per-process
    # telemetry shard (on waitall and on the preemption drain) that the
    # parent folds back with telemetry.merge()
    env["MXNET_TELEMETRY_DIR"] = _drill_telemetry_dir(root)
    return env


def _run_child(argv: List[str], env: Dict[str, str],
               timeout: float = 300.0) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.drills"] + argv,
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO)


def _train_child(root: str, scen_dir: str, label: str, devices: int,
                 stop_at: int = N_STEPS, sigterm_at: Optional[int] = None,
                 sigkill_at: Optional[int] = None, delay: float = 0.0,
                 preempt: bool = False, ckpt_name: str = "ckpt",
                 sentinel_every: int = 0,
                 bitflip_at: Optional[int] = None, bitflip_dev: int = 0,
                 poison_at: Optional[int] = None,
                 timeout: float = 300.0) -> subprocess.CompletedProcess:
    os.makedirs(scen_dir, exist_ok=True)
    argv = ["train", "--dir", scen_dir,
            "--ckpt", os.path.join(scen_dir, ckpt_name),
            "--label", label, "--stop-at", str(stop_at),
            "--save-every", str(SAVE_EVERY), "--delay", str(delay)]
    if sigterm_at is not None:
        argv += ["--sigterm-at", str(sigterm_at)]
    if sigkill_at is not None:
        argv += ["--sigkill-at", str(sigkill_at)]
    if preempt:
        argv += ["--preempt"]
    if sentinel_every:
        argv += ["--sentinel-every", str(sentinel_every)]
    if bitflip_at is not None:
        argv += ["--bitflip-at", str(bitflip_at),
                 "--bitflip-dev", str(bitflip_dev)]
    if poison_at is not None:
        argv += ["--poison-at", str(poison_at)]
    return _run_child(argv, _child_env(root, devices), timeout=timeout)


def _read_losses(scen_dir: str, label: str) -> Dict[int, str]:
    path = os.path.join(scen_dir, f"losses-{label}.txt")
    out: Dict[int, str] = {}
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 2:
                out[int(parts[0])] = parts[1]    # later replay wins
    return out


def _read_result(scen_dir: str, label: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(scen_dir, f"result-{label}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _tmp_litter(ckpt_dir: str) -> List[str]:
    if not os.path.isdir(ckpt_dir):
        return []
    return [f for f in os.listdir(ckpt_dir) if f.endswith(".tmp")]


def _ensure_reference(root: str, failures: List[str]) -> Dict[int, str]:
    """The memoized uninterrupted 4-device reference run (shared by
    every train scenario under ``root``; also warms the disk cache)."""
    scen_dir = os.path.join(root, "ref4")
    if _read_result(scen_dir, "ref") is None:
        r = _train_child(root, scen_dir, "ref", devices=4)
        if r.returncode != 0:
            failures.append(
                f"reference run failed rc={r.returncode}: "
                f"{r.stderr[-1500:]}")
            return {}
    losses = _read_losses(scen_dir, "ref")
    if len(losses) != N_STEPS:
        failures.append(
            f"reference run produced {len(losses)}/{N_STEPS} loss lines")
    return losses


def _check_resumed_trajectory(failures: List[str], ref: Dict[int, str],
                              first: Dict[int, str],
                              resumed: Dict[int, str],
                              restored_at: int, what: str) -> int:
    """Merged first-run + resumed losses must equal the reference
    bit-for-bit, and replayed overlap must equal the first run's —
    recovery neither loses, doubles, nor perturbs a step."""
    checked = 0
    for i in range(N_STEPS):
        want = ref.get(i)
        got = resumed.get(i) if i >= restored_at else first.get(i)
        if want is None or got is None:
            failures.append(f"{what}: step {i} missing a loss line")
            continue
        if want != got:
            failures.append(
                f"{what}: step {i} loss {got} != reference {want}")
        checked += 1
    for i, v in resumed.items():
        if i in first and first[i] != v:
            failures.append(
                f"{what}: replayed step {i} diverged from the first "
                f"run ({v} != {first[i]})")
    return checked


def run_drill(name: str, root: str, verbose: bool = False
              ) -> Dict[str, Any]:
    """Run one scenario under ``root`` (shared pcache + reference) and
    return its report: ``ok``, ``failures``, and the measured recovery
    budget (recovery_s / recovery_wall_s / steps_replayed / drain_s /
    fresh_compiles / disk hits)."""
    if name not in SCENARIOS and name not in ROUTER_SCENARIOS:
        raise ValueError(f"unknown drill {name!r} (one of "
                         f"{SCENARIOS + ROUTER_SCENARIOS})")
    os.makedirs(root, exist_ok=True)
    failures: List[str] = []
    report: Dict[str, Any] = {"scenario": name, "root": root}
    t0 = time.monotonic()
    if name in ROUTER_SCENARIOS:
        _drill_router(root, failures, report,
                      mode=(name[len("router_"):]
                            if name.startswith("router_") else name))
    elif name == "decode_drain":
        _drill_decode(root, failures, report)
    else:
        ref = _ensure_reference(root, failures)
        if not failures:
            {"sigterm_drain": _drill_sigterm,
             "sigkill_between_saves": _drill_sigkill,
             "topology_change": _drill_topology,
             "corrupt_latest": _drill_corrupt,
             "bitflip_param": _drill_bitflip,
             "loss_spike": _drill_loss_spike}[name](root, ref, failures,
                                                    report)
    report["ok"] = not failures
    report["failures"] = failures
    report["drill_wall_s"] = round(time.monotonic() - t0, 3)
    if verbose:
        print(json.dumps(report, indent=2, default=str))
    return report


def _resume_budget(report: Dict[str, Any], res: Dict[str, Any]) -> None:
    disk = res.get("disk") or {}
    report.update({
        "recovery_s": res.get("recovery_s"),
        "recovery_wall_s": res.get("first_step_s"),
        "steps_replayed": res.get("steps_replayed"),
        "restored_at": res.get("restored_at"),
        "fresh_compiles": disk.get("misses"),
        "disk_hits": disk.get("hits"),
        "resume_telemetry": res.get("telemetry"),
    })


def _drill_sigterm(root: str, ref: Dict[int, str], failures: List[str],
                   report: Dict[str, Any]) -> None:
    scen = os.path.join(root, "sigterm")
    kill_at = 9                       # mid-step, not on a save boundary
    c1 = _train_child(root, scen, "c1", devices=4, sigterm_at=kill_at,
                      preempt=True)
    res1 = _read_result(scen, "c1") or {}
    want_code = res1.get("preempted_code") or 83
    if c1.returncode != want_code:
        failures.append(
            f"sigterm child exited {c1.returncode}, wanted the "
            f"distinguished code {want_code}: {c1.stderr[-1500:]}")
    report["drain_s"] = res1.get("drain_s")
    report["exit_code_c1"] = c1.returncode
    if res1.get("drain_s") is None or res1.get("drain_s") <= 0:
        failures.append("sigterm drain recorded no preemption.drain_s")
    c2 = _train_child(root, scen, "c2", devices=4)
    if c2.returncode != 0:
        failures.append(f"sigterm resume failed rc={c2.returncode}: "
                        f"{c2.stderr[-1500:]}")
        return
    res2 = _read_result(scen, "c2") or {}
    _resume_budget(report, res2)
    first = _read_losses(scen, "c1")
    # graceful drain checkpointed the LAST COMPLETED step: 0 replay
    # (replay = steps the first process ran past the restore point)
    restored = res2.get("restored_at") or 0
    replay = max(0, (max(first) + 1 if first else 0) - restored)
    report["steps_replayed"] = replay
    if res2.get("restored_at") != kill_at:
        failures.append(
            f"sigterm resume restored step {res2.get('restored_at')}, "
            f"wanted the drained step {kill_at}")
    if replay != 0:
        failures.append(
            f"graceful drain must replay 0 steps, resume replayed "
            f"{replay}")
    if (res2.get("disk") or {}).get("misses") != 0:
        failures.append(
            f"sigterm warm resume performed "
            f"{(res2.get('disk') or {}).get('misses')} fresh compiles "
            "(wanted 0: disk hits only)")
    _check_resumed_trajectory(
        failures, ref, first, _read_losses(scen, "c2"), restored,
        "sigterm")
    report["leaked_tmp"] = _tmp_litter(os.path.join(scen, "ckpt"))
    if report["leaked_tmp"]:
        failures.append(f"sigterm left temp litter {report['leaked_tmp']}")


def _drill_sigkill(root: str, ref: Dict[int, str], failures: List[str],
                   report: Dict[str, Any]) -> None:
    scen = os.path.join(root, "sigkill")
    kill_at = 10                     # 2 past the last periodic save (8)
    c1 = _train_child(root, scen, "c1", devices=4, sigkill_at=kill_at)
    if c1.returncode != -signal.SIGKILL:
        failures.append(
            f"sigkill child exited {c1.returncode}, wanted "
            f"{-signal.SIGKILL}")
    report["exit_code_c1"] = c1.returncode
    c2 = _train_child(root, scen, "c2", devices=4)
    if c2.returncode != 0:
        failures.append(f"sigkill resume failed rc={c2.returncode}: "
                        f"{c2.stderr[-1500:]}")
        return
    res2 = _read_result(scen, "c2") or {}
    _resume_budget(report, res2)
    first = _read_losses(scen, "c1")
    restored = res2.get("restored_at") or 0
    replay = max(0, (max(first) + 1 if first else 0) - restored)
    report["steps_replayed"] = replay
    expect_restore = kill_at - (kill_at % SAVE_EVERY)
    if res2.get("restored_at") != expect_restore:
        failures.append(
            f"sigkill resume restored step {res2.get('restored_at')}, "
            f"wanted the last complete save {expect_restore}")
    if replay != kill_at - expect_restore:
        failures.append(
            f"sigkill resume replayed {replay} steps, wanted "
            f"{kill_at - expect_restore} (the save gap)")
    if (res2.get("disk") or {}).get("misses") != 0:
        failures.append(
            f"sigkill warm resume performed "
            f"{(res2.get('disk') or {}).get('misses')} fresh compiles "
            "(wanted 0: disk hits only)")
    _check_resumed_trajectory(
        failures, ref, first, _read_losses(scen, "c2"), restored,
        "sigkill")
    report["leaked_tmp"] = _tmp_litter(os.path.join(scen, "ckpt"))
    if report["leaked_tmp"]:
        failures.append(f"sigkill left temp litter {report['leaked_tmp']}")


def _drill_topology(root: str, ref: Dict[int, str], failures: List[str],
                    report: Dict[str, Any]) -> None:
    scen = os.path.join(root, "topology")
    c1 = _train_child(root, scen, "c1", devices=4, stop_at=HALF)
    if c1.returncode != 0:
        failures.append(f"topology 4-dev leg failed rc={c1.returncode}: "
                        f"{c1.stderr[-1500:]}")
        return
    res1 = _read_result(scen, "c1") or {}
    losses = {}
    import shutil

    for label in ("c2", "c2b"):       # the pair: determinism + warm cache
        # each resume gets its OWN copy of the 4-device checkpoint dir
        # (a shared dir would let c2's later saves turn c2b's restore
        # into a no-op)
        ckpt_name = f"ckpt-{label}"
        dst = os.path.join(scen, ckpt_name)
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        shutil.copytree(os.path.join(scen, "ckpt"), dst)
        r = _train_child(root, scen, label, devices=2,
                         ckpt_name=ckpt_name)
        if r.returncode != 0:
            failures.append(
                f"topology 2-dev resume {label} failed "
                f"rc={r.returncode}: {r.stderr[-1500:]}")
            return
        losses[label] = _read_losses(scen, label)
    res2 = _read_result(scen, "c2") or {}
    res2b = _read_result(scen, "c2b") or {}
    _resume_budget(report, res2b)     # the WARM-cache recovery numbers
    if res2.get("restored_at") != HALF:
        failures.append(
            f"topology resume restored step {res2.get('restored_at')}, "
            f"wanted {HALF}")
    # bit-exact re-placement: the digest over the params RESTORED onto
    # the 2-device mesh must equal the 4-device saver's final params
    if res2.get("restored_params_sha") != res1.get("params_sha"):
        failures.append(
            "topology restore(like=) onto the 2-device mesh did not "
            "reproduce the 4-device params bit-exactly "
            f"({res2.get('restored_params_sha')} != "
            f"{res1.get('params_sha')})")
    if res2.get("params_sha") != res2b.get("params_sha"):
        failures.append("topology determinism pair diverged in final "
                        "params (recovery is not deterministic)")
    if losses["c2"] != losses["c2b"]:
        failures.append("topology determinism pair diverged in losses")
    # cross-mesh trajectory: tracks the 4-dev reference within tolerance
    for i in range(HALF, N_STEPS):
        w = ref.get(i)
        g = losses["c2"].get(i)
        if w is None or g is None:
            failures.append(f"topology: step {i} missing a loss line")
            continue
        wf, gf = float.fromhex(w), float.fromhex(g)
        if abs(wf - gf) > TOPO_RTOL * max(1.0, abs(wf)):
            failures.append(
                f"topology: step {i} loss {gf} drifted past rtol "
                f"{TOPO_RTOL} from the 4-dev reference {wf}")
    # warm persistent cache: the SECOND 2-dev resume recompiles nothing
    fresh = (res2b.get("disk") or {}).get("misses")
    if fresh != 0:
        failures.append(
            f"topology warm resume performed {fresh} fresh compiles "
            "(wanted 0 — every program from MXNET_PROGRAM_CACHE_DIR)")
    report["params_sha_c1"] = res1.get("params_sha")


def _drill_corrupt(root: str, ref: Dict[int, str], failures: List[str],
                   report: Dict[str, Any]) -> None:
    scen = os.path.join(root, "corrupt")
    c1 = _train_child(root, scen, "c1", devices=4, stop_at=HALF)
    if c1.returncode != 0:
        failures.append(f"corrupt setup leg failed rc={c1.returncode}: "
                        f"{c1.stderr[-1500:]}")
        return
    # flip one payload byte of the NEWEST checkpoint; its sha256 sidecar
    # now disagrees even though the pickle may still load
    ckpt_dir = os.path.join(scen, "ckpt")
    target = os.path.join(ckpt_dir, f"ckpt-{HALF}.pkl")
    with open(target, "r+b") as f:
        f.seek(-7, os.SEEK_END)
        b = f.read(1)
        f.seek(-7, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    c2 = _train_child(root, scen, "c2", devices=4)
    if c2.returncode != 0:
        failures.append(f"corrupt resume failed rc={c2.returncode}: "
                        f"{c2.stderr[-1500:]}")
        return
    res2 = _read_result(scen, "c2") or {}
    _resume_budget(report, res2)
    first = _read_losses(scen, "c1")
    restored = res2.get("restored_at") or 0
    report["steps_replayed"] = max(
        0, (max(first) + 1 if first else 0) - restored)
    expect = HALF - SAVE_EVERY
    if res2.get("restored_at") != expect:
        failures.append(
            f"corrupt resume restored step {res2.get('restored_at')}, "
            f"wanted degradation to the previous complete step {expect}")
    if not res2.get("digest_mismatches"):
        failures.append("corrupt resume counted no "
                        "checkpoint.digest_mismatches")
    _check_resumed_trajectory(
        failures, ref, _read_losses(scen, "c1"), _read_losses(scen, "c2"),
        res2.get("restored_at") or 0, "corrupt")


def _merged_losses_vs_reference(failures: List[str], ref: Dict[int, str],
                                merged: Dict[int, str],
                                what: str) -> None:
    """An in-process rollback drill writes BOTH the tainted and the
    replayed loss lines to one file; last-line-wins merging must equal
    the uninterrupted reference bit-for-bit (rollback healed the run)."""
    for i in range(N_STEPS):
        want, got = ref.get(i), merged.get(i)
        if want is None or got is None:
            failures.append(f"{what}: step {i} missing a loss line")
        elif want != got:
            failures.append(
                f"{what}: post-rollback step {i} loss {got} != "
                f"reference {want}")


def _drill_bitflip(root: str, ref: Dict[int, str], failures: List[str],
                   report: Dict[str, Any]) -> None:
    """Silent corruption end-to-end: one flipped bit on one replica ->
    vote localizes the device -> rollback -> bit-exact resume ->
    restart excludes the quarantined device from the mesh."""
    scen = os.path.join(root, "bitflip")
    flip_at, flip_dev = 13, 2          # mid save-window, device pos 2
    c1 = _train_child(root, scen, "c1", devices=4,
                      sentinel_every=SAVE_EVERY,
                      bitflip_at=flip_at, bitflip_dev=flip_dev)
    if c1.returncode != 0:
        failures.append(f"bitflip child failed rc={c1.returncode}: "
                        f"{c1.stderr[-1500:]}")
        return
    res1 = _read_result(scen, "c1") or {}
    _resume_budget(report, res1)       # the in-process rollback budget
    report["steps_replayed"] = res1.get("steps_replayed")
    report["flipped_device"] = res1.get("flipped_device")
    report["quarantine"] = res1.get("quarantine")
    victim = res1.get("flipped_device")
    if res1.get("restarts") != 1:
        failures.append(
            f"bitflip run took {res1.get('restarts')} restarts, wanted "
            "exactly 1 (the sentinel rollback)")
    if not res1.get("replica_divergence"):
        failures.append("bitflip vote counted no "
                        "sentinel.replica_divergence")
    if not res1.get("rollbacks"):
        failures.append("bitflip counted no sentinel.rollbacks")
    named = {e.get("device") for e in res1.get("corruption_events") or []
             if e.get("name") == "sentinel"}
    if victim not in named:
        failures.append(
            f"bitflip corruption events named devices {sorted(named)}, "
            f"not the corrupted device {victim}")
    q = res1.get("quarantine") or []
    if victim not in [e["id"] for e in q if e["kind"] == "device"]:
        failures.append(
            f"bitflip quarantine {q} does not hold device {victim}")
    # detection within one sentinel cadence: the rollback's restore
    # point + replay gap locate the verdict step
    restored = res1.get("restored_at")
    detected = (restored or 0) + (res1.get("steps_replayed") or 0)
    if restored != flip_at - (flip_at % SAVE_EVERY):
        failures.append(
            f"bitflip restored step {restored}, wanted the last "
            f"verified save {flip_at - (flip_at % SAVE_EVERY)}")
    if not (0 < detected - flip_at <= SAVE_EVERY):
        failures.append(
            f"bitflip detected at step {detected}, flip at {flip_at} — "
            f"outside one sentinel cadence ({SAVE_EVERY})")
    # rollback healed the run: merged losses == the uninterrupted
    # reference bit-for-bit (the flip and the tainted steps left no
    # trace), at 0 fresh compiles (the ref leg warmed the disk cache;
    # rollback replays reuse the SAME program)
    _merged_losses_vs_reference(
        failures, ref, _read_losses(scen, "c1"), "bitflip")
    if (res1.get("disk") or {}).get("misses") != 0:
        failures.append(
            f"bitflip rollback performed "
            f"{(res1.get('disk') or {}).get('misses')} fresh compiles "
            "(wanted 0: same mesh, same program)")
    # restart: the persisted quarantine re-resolves the mesh WITHOUT
    # the suspect (the PR-11 topology machinery, triggered
    # automatically); run a few extra steps on the smaller mesh
    c2 = _train_child(root, scen, "c2", devices=4,
                      sentinel_every=SAVE_EVERY, stop_at=N_STEPS + 6)
    if c2.returncode != 0:
        failures.append(f"bitflip quarantined restart failed "
                        f"rc={c2.returncode}: {c2.stderr[-1500:]}")
        return
    res2 = _read_result(scen, "c2") or {}
    mesh2 = res2.get("mesh_devices")
    report["restart_mesh_devices"] = mesh2
    if mesh2 is None or len(mesh2) != 3 or victim in mesh2:
        failures.append(
            f"bitflip restart resolved mesh {mesh2}; wanted 3 devices "
            f"excluding the quarantined device {victim}")
    if res2.get("restored_at") != N_STEPS:
        failures.append(
            f"bitflip restart restored step {res2.get('restored_at')}, "
            f"wanted {N_STEPS} (resume onto the quarantined mesh)")
    if res2.get("steps_run") != N_STEPS + 6:
        failures.append(
            f"bitflip restart ran {res2.get('steps_run')} steps, "
            f"wanted {N_STEPS + 6}")


def _drill_loss_spike(root: str, ref: Dict[int, str],
                      failures: List[str],
                      report: Dict[str, Any]) -> None:
    """Scripted poisoned batch: the z-score window trips at the next
    checkpoint boundary (the tainted state is never saved), rollback
    replays exactly the save-interval gap, merged trajectory bit-exact."""
    scen = os.path.join(root, "spike")
    poison_at = 13
    c1 = _train_child(root, scen, "c1", devices=4,
                      sentinel_every=SAVE_EVERY, poison_at=poison_at)
    if c1.returncode != 0:
        failures.append(f"loss_spike child failed rc={c1.returncode}: "
                        f"{c1.stderr[-1500:]}")
        return
    res1 = _read_result(scen, "c1") or {}
    _resume_budget(report, res1)
    report["steps_replayed"] = res1.get("steps_replayed")
    report["last_rollback"] = res1.get("last_rollback")
    if res1.get("restarts") != 1:
        failures.append(
            f"loss_spike took {res1.get('restarts')} restarts, wanted "
            "exactly 1 (the windowed rollback)")
    if not res1.get("rollbacks"):
        failures.append("loss_spike counted no sentinel.rollbacks")
    if res1.get("replica_divergence"):
        failures.append(
            "loss_spike counted replica divergence — a poisoned batch "
            "perturbs every replica identically; the vote must stay "
            "unanimous")
    reason = (res1.get("last_rollback") or {}).get("reason")
    if reason not in ("grad_norm_anomaly", "loss_anomaly"):
        failures.append(
            f"loss_spike rollback reason {reason!r}, wanted the "
            "windowed z-score detector")
    expect_restore = poison_at - (poison_at % SAVE_EVERY)
    if res1.get("restored_at") != expect_restore:
        failures.append(
            f"loss_spike restored step {res1.get('restored_at')}, "
            f"wanted the last pre-poison save {expect_restore}")
    if res1.get("steps_replayed") != SAVE_EVERY:
        failures.append(
            f"loss_spike replayed {res1.get('steps_replayed')} steps, "
            f"wanted exactly the save-window gap {SAVE_EVERY}")
    _merged_losses_vs_reference(
        failures, ref, _read_losses(scen, "c1"), "loss_spike")
    if (res1.get("disk") or {}).get("misses") != 0:
        failures.append(
            f"loss_spike rollback performed "
            f"{(res1.get('disk') or {}).get('misses')} fresh compiles "
            "(wanted 0)")


def _drill_decode(root: str, failures: List[str],
                  report: Dict[str, Any]) -> None:
    scen = os.path.join(root, "decode")
    os.makedirs(scen, exist_ok=True)
    req_ids = list(range(8))
    argv = ["decode", "--dir", scen, "--label", "c1", "--preempt",
            "--self-sigterm", "--max-new", "12",
            "--requests", ",".join(map(str, req_ids))]
    c1 = _run_child(argv, _child_env(root, 1))
    res1 = _read_result(scen, "c1") or {}
    code = res1.get("preempted_code") or 83
    report["exit_code_c1"] = c1.returncode
    report["drain_s"] = res1.get("drain_s")
    if c1.returncode != code:
        failures.append(
            f"decode child exited {c1.returncode}, wanted the "
            f"distinguished code {code}: {c1.stderr[-1500:]}")
        return
    delivered = {int(k): v for k, v in (res1.get("delivered") or {}).items()}
    shed = {int(k): v for k, v in (res1.get("shed") or {}).items()}
    if set(delivered) | set(shed) != set(req_ids):
        failures.append(
            f"decode drain lost requests: delivered {sorted(delivered)} "
            f"+ shed {sorted(shed)} != {req_ids}")
    if not delivered:
        failures.append("decode drain delivered nothing before the "
                        "notice (self-trigger broken)")
    if not shed:
        failures.append("decode drain shed nothing — the queue was "
                        "empty at the notice (drill not mid-stream)")
    bad_kinds = {r: k for r, k in shed.items() if k != "draining"}
    if bad_kinds:
        failures.append(f"decode sheds were not typed 'draining': "
                        f"{bad_kinds}")
    if not res1.get("token_exact"):
        failures.append("decode in-flight completions were not "
                        "token-exact vs the eager oracle")
    if res1.get("pool_in_use") != 0:
        failures.append(
            f"decode drain leaked {res1.get('pool_in_use')} KV pages")
    report["leaked_pages"] = res1.get("pool_in_use")
    # restart: the shed requests re-queue on a fresh process, token-exact
    if shed:
        argv = ["decode", "--dir", scen, "--label", "c2",
                "--max-new", "12",
                "--requests", ",".join(str(r) for r in sorted(shed))]
        c2 = _run_child(argv, _child_env(root, 1))
        res2 = _read_result(scen, "c2") or {}
        if c2.returncode != 0:
            failures.append(f"decode re-queue leg failed "
                            f"rc={c2.returncode}: {c2.stderr[-1500:]}")
            return
        redone = {int(k) for k in (res2.get("delivered") or {})}
        if redone != set(shed):
            failures.append(
                f"decode re-queue delivered {sorted(redone)} != shed "
                f"{sorted(shed)}")
        if not res2.get("token_exact"):
            failures.append("decode re-queued requests were not "
                            "token-exact")
        if res2.get("pool_in_use") != 0:
            failures.append("decode re-queue leg leaked pages")


def _check_child_shard(root: str, failures: List[str],
                       report: Dict[str, Any], res: Dict[str, Any],
                       what: str, counters: Dict[str, Any]) -> None:
    """Fold the drill's telemetry shards (``telemetry.merge``) and pin
    the named counters of the child's OWN shard against the totals the
    child reported in its result JSON — the cross-process aggregation
    path proven against ground truth the parent already holds."""
    from mxnet_tpu import telemetry as _tel

    tel_dir = _drill_telemetry_dir(root)
    if not os.path.isdir(tel_dir):
        failures.append(f"{what}: no telemetry shard dir at {tel_dir}")
        return
    merged = _tel.merge(tel_dir)
    report["telemetry_shards"] = len(merged["shards"])
    pid = res.get("pid")
    proc = next((p for p in merged["processes"] if p["pid"] == pid), None)
    if proc is None:
        failures.append(
            f"{what}: no telemetry shard for child pid {pid} "
            f"(shards: {merged['shards']})")
        return
    shard = _tel._read_shard(os.path.join(tel_dir, proc["shard"]))
    snap = (shard["snapshot"] or {}).get("counters", {})
    for name, want in counters.items():
        got = snap.get(name)
        if want is not None and got != want:
            failures.append(
                f"{what}: merged shard counter {name}={got} != "
                f"child-observed {want}")
    # and the FLEET fold can only ever hold at least the child's total
    for name, want in counters.items():
        fleet = merged["counters"].get(name)
        if want is not None and fleet is not None and fleet < want:
            failures.append(
                f"{what}: fleet-merged {name}={fleet} < child's {want}")


def _drill_router(root: str, failures: List[str],
                  report: Dict[str, Any], mode: str) -> None:
    """One cell of the serving chaos matrix: a 2-replica router child
    under {kill | wedge | flap | deadline_storm | prefix_storm}.  The
    availability contract every cell shares: 0 dropped requests (every
    submission ends delivered or typed-shed), every delivery
    token-exact vs the eager oracle, 0 leaked KV pages, and a clean
    page-pool refcount audit at drain (ISSUE 16: no page leaked,
    double-freed, or indexed while dead)."""
    scen = os.path.join(root, f"router-{mode}")
    os.makedirs(scen, exist_ok=True)
    argv = ["router", "--dir", scen, "--label", "c1", "--mode", mode,
            "--steady", "12", "--requests", "8", "--max-new", "10"]
    if mode in ("kill", "prefix_storm"):
        argv += ["--preempt"]
    # the fleet cells spawn replica subprocesses (a JAX boot each):
    # give the child a longer leash than the in-process cells
    timeout = 600.0 if mode in ("scale_storm", "host_loss") else 300.0
    c1 = _run_child(argv, _child_env(root, 1), timeout=timeout)
    res = _read_result(scen, "c1") or {}
    report["exit_code_c1"] = c1.returncode
    want_code = ((res.get("preempted_code") or 83)
                 if mode in ("kill", "prefix_storm") else 0)
    if c1.returncode != want_code:
        failures.append(
            f"router[{mode}] child exited {c1.returncode}, wanted "
            f"{want_code}: {c1.stderr[-1500:]}")
        return
    records = {int(k): v for k, v in (res.get("records") or {}).items()}
    submitted = (len(res.get("steady_ids") or [])
                 + len(res.get("chaos_ids") or [])
                 + len(res.get("drain_ids") or []))
    # 0 dropped: every request the child submitted has a typed outcome
    errors = {r: v for r, v in records.items() if v["status"] == "error"}
    if errors:
        failures.append(
            f"router[{mode}] requests errored instead of "
            f"delivering/shedding: {errors}")
    known = sum(1 for v in records.values()
                if v["status"] in ("delivered", "shed"))
    if len(records) < submitted:
        failures.append(
            f"router[{mode}] dropped requests: {len(records)} outcomes "
            f"for {submitted} submissions")
    report["dropped"] = max(0, submitted - known)
    if not res.get("token_exact"):
        failures.append(
            f"router[{mode}] delivered responses were not token-exact "
            "vs the eager oracle (failover/hedge broke greedy "
            "idempotence)")
    if res.get("leaked_pages"):
        failures.append(
            f"router[{mode}] leaked {res['leaked_pages']} KV pages")
    report["leaked_pages"] = res.get("leaked_pages")
    if res.get("pool_audit"):
        failures.append(
            f"router[{mode}] page-pool refcount audit failed at drain: "
            f"{res['pool_audit']}")
    rt = res.get("router") or {}
    # ISSUE-15 fleet aggregation: the child flushed an atomic telemetry
    # shard; merging it back must reproduce the failover/shed/delivered
    # totals the parent observed in the child's own result record —
    # cross-process counters survive the round trip exactly
    _check_child_shard(root, failures, report, res, what=f"router[{mode}]",
                       counters={
                           "serving.router0.failovers": rt.get("failovers"),
                           "serving.router0.sheds": rt.get("sheds"),
                           "serving.router0.delivered": rt.get("delivered"),
                       })
    chaos = [records[r] for r in (res.get("chaos_ids") or [])
             if r in records]
    chaos_lat = sorted(v["elapsed_s"] for v in chaos
                       if v["status"] == "delivered")
    report["steady_p99_s"] = res.get("steady_p99_s")
    report["chaos_p99_s"] = (
        chaos_lat[min(len(chaos_lat) - 1, int(len(chaos_lat) * 0.99))]
        if chaos_lat else None)
    report["failovers"] = rt.get("failovers")
    report["hedges"] = rt.get("hedges")
    report["breaker_opens"] = rt.get("breaker_opens")
    report["breaker_closes"] = rt.get("breaker_closes")
    report["re_admit_s"] = res.get("re_admit_s")
    report["drain_s"] = res.get("drain_s")

    if mode == "kill":
        if not rt.get("failovers"):
            failures.append("router[kill] counted no failovers — the "
                            "dead replica's requests were not re-routed")
        if not rt.get("breaker_opens"):
            failures.append("router[kill] never opened the dead "
                            "replica's breaker")
        drain_recs = [records[r] for r in (res.get("drain_ids") or [])
                      if r in records]
        bad = [v for v in drain_recs
               if v["status"] == "shed" and v.get("kind") != "draining"]
        if bad:
            failures.append(
                f"router[kill] drain-phase sheds were not typed "
                f"'draining': {bad}")
        if res.get("drain_s") is None:
            failures.append("router[kill] preemption drain recorded no "
                            "preemption.drain_s — waitall did not drain "
                            "the router")
    elif mode == "wedge":
        if not rt.get("wedged"):
            failures.append("router[wedge] never declared the wedged "
                            "dispatch (heartbeat eviction broken)")
        if not rt.get("failovers"):
            failures.append("router[wedge] counted no failovers")
    elif mode == "flap":
        if not rt.get("breaker_opens"):
            failures.append("router[flap] flap burst never opened the "
                            "breaker")
        if not rt.get("breaker_closes"):
            failures.append("router[flap] breaker never closed again "
                            "(half-open probe re-admission broken)")
        if res.get("re_admit_s") is None:
            failures.append("router[flap] re-admission never observed")
    elif mode == "prefix_storm":
        # ISSUE 16: shared-prefix storm + replica kill.  The affinity
        # weight converged the storm onto replica 0's warm cache, so the
        # kill lands on exactly the replica holding the shared pages —
        # failover must rebuild the prefix cold on replica 1 with zero
        # refcount damage.
        if not rt.get("failovers"):
            failures.append("router[prefix_storm] counted no failovers — "
                            "the warm replica's requests were not "
                            "re-routed after the kill")
        if not rt.get("breaker_opens"):
            failures.append("router[prefix_storm] never opened the dead "
                            "replica's breaker")
        if not res.get("prefix_hit_blocks"):
            failures.append(
                "router[prefix_storm] counted 0 prefix.hit_blocks — the "
                "shared system prompt never hit the content-addressed "
                "cache (affinity or publish broken)")
        drain_recs = [records[r] for r in (res.get("drain_ids") or [])
                      if r in records]
        bad = [v for v in drain_recs
               if v["status"] == "shed" and v.get("kind") != "draining"]
        if bad:
            failures.append(
                f"router[prefix_storm] drain-phase sheds were not typed "
                f"'draining': {bad}")
        report["prefix_hit_blocks"] = res.get("prefix_hit_blocks")
        report["prefix_miss_blocks"] = res.get("prefix_miss_blocks")
        report["prefix_hit_rate"] = res.get("prefix_hit_rate")
        report["prefix_cow_forks"] = res.get("prefix_cow_forks")
    elif mode == "scale_storm":
        fleet = (rt.get("fleet") or {})
        remotes = res.get("remotes") or []
        report["fleet"] = fleet
        report["remotes"] = remotes
        report["join_to_first_served_s"] = max(
            (r["first_served_s"] for r in remotes
             if r.get("first_served_s") is not None), default=None)
        if fleet.get("scale_ups", 0) < 2:
            failures.append(
                f"router[scale_storm] autoscaler counted "
                f"{fleet.get('scale_ups')} scale_ups, wanted >=2 "
                "(the fleet never reached 3 replicas)")
        if fleet.get("scale_downs", 0) < 2:
            failures.append(
                f"router[scale_storm] autoscaler counted "
                f"{fleet.get('scale_downs')} scale_downs, wanted >=2 "
                "(the fleet never shrank back)")
        if fleet.get("drains", 0) < 2:
            failures.append(
                "router[scale_storm] scale-down skipped the graceful "
                f"drain ({fleet.get('drains')} drains for "
                f"{fleet.get('scale_downs')} scale_downs)")
        states = res.get("replica_states") or []
        if sum(1 for s in states if s == "serving") != 1:
            failures.append(
                f"router[scale_storm] fleet did not settle back to 1 "
                f"SERVING replica: {states}")
        for r in remotes:
            if r.get("exit_code") != 83:
                failures.append(
                    f"router[scale_storm] remote {r.get('label')} "
                    f"exited {r.get('exit_code')}, wanted the "
                    "distinguished preemption code 83")
            if r.get("fresh_compiles"):
                failures.append(
                    f"router[scale_storm] remote {r.get('label')} "
                    f"performed {r['fresh_compiles']} fresh compiles "
                    "(wanted 0: warm join off the shared program cache)")
            if r.get("leaked_pages"):
                failures.append(
                    f"router[scale_storm] remote {r.get('label')} "
                    f"leaked {r['leaked_pages']} KV pages")
            if r.get("pool_audit"):
                failures.append(
                    f"router[scale_storm] remote {r.get('label')} "
                    f"pool audit failed: {r['pool_audit']}")
            if r.get("first_served_s") is None:
                failures.append(
                    f"router[scale_storm] remote {r.get('label')} "
                    "joined but never served a request")
        if res.get("queued_at_preempt", 0) > 2:
            sheds = sum(int(r.get("shed_draining") or 0)
                        for r in remotes)
            if not sheds:
                failures.append(
                    "router[scale_storm] preempt-under-load had "
                    f"{res['queued_at_preempt']} rows queued on the "
                    "victim but no typed draining shed came back over "
                    "the wire (the handback path never ran)")
    elif mode == "host_loss":
        report["kill_to_recovered_s"] = res.get("kill_to_recovered_s")
        if not rt.get("failovers"):
            failures.append(
                "router[host_loss] counted no failovers — the killed "
                "host's requests were not re-routed")
        if res.get("kill_to_recovered_s") is None:
            failures.append(
                "router[host_loss] never delivered a request after the "
                "SIGKILL (the fleet did not recover)")
        if not rt.get("breaker_opens"):
            failures.append(
                "router[host_loss] never opened the dead host's "
                "breaker")
    elif mode == "spec_draft_poison":
        # ISSUE 19: a poisoned draft must cost ZERO availability — the
        # engines auto-disable speculation via the cost-table path and
        # degrade to plain decode in-place; the shared contract above
        # (0 dropped, token-exact, clean audit) already holds, so the
        # cell-specific checks are about the disable machinery itself
        spec = res.get("spec") or []
        report["spec"] = spec
        report["spec_autodisabled"] = int(
            (res.get("telemetry") or {}).get("spec.autodisabled", 0))
        if not any(s.get("spec_rounds") for s in spec):
            failures.append(
                "router[spec_draft_poison] steady phase never engaged "
                "speculation (0 spec rounds before the poison — the "
                "cell exercised nothing)")
        if not all(s.get("spec_disabled") for s in spec):
            failures.append(
                "router[spec_draft_poison] a poisoned replica did not "
                f"auto-disable speculation: {spec}")
        if report["spec_autodisabled"] < 1:
            failures.append(
                "router[spec_draft_poison] no spec.autodisabled event "
                "was counted despite the poisoned draft")
    elif mode == "deadline_storm":
        for r, v in sorted(records.items()):
            b = v.get("budget_s")
            if b is None:
                continue
            if b < 0.01:                      # the infeasible budgets
                if v["status"] != "shed" or v.get("kind") != "deadline":
                    failures.append(
                        f"router[deadline_storm] request {r} with a "
                        f"{b * 1e6:.0f}us budget ended "
                        f"{v['status']}:{v.get('kind')} (wanted a "
                        "typed 'deadline' shed)")
                if v["elapsed_s"] > b + 1.0:
                    failures.append(
                        f"router[deadline_storm] request {r} consumed "
                        f"{v['elapsed_s']:.3f}s against a "
                        f"{b:.3f}s budget (+1s slack) — the deadline "
                        "did not bound the wait")
            elif v["status"] != "delivered":
                failures.append(
                    f"router[deadline_storm] feasible request {r} "
                    f"ended {v['status']}:{v.get('kind')}")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="mxnet_tpu.drills",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train-drill child")
    t.add_argument("--dir", required=True)
    t.add_argument("--ckpt", required=True)
    t.add_argument("--label", default="c1")
    t.add_argument("--stop-at", type=int, default=N_STEPS,
                   dest="stop_at")
    t.add_argument("--save-every", type=int, default=SAVE_EVERY,
                   dest="save_every")
    t.add_argument("--max-restarts", type=int, default=3,
                   dest="max_restarts")
    t.add_argument("--delay", type=float, default=0.0)
    t.add_argument("--sigterm-at", type=int, default=None,
                   dest="sigterm_at")
    t.add_argument("--sigkill-at", type=int, default=None,
                   dest="sigkill_at")
    t.add_argument("--preempt", action="store_true")
    t.add_argument("--sentinel-every", type=int, default=0,
                   dest="sentinel_every")
    t.add_argument("--bitflip-at", type=int, default=None,
                   dest="bitflip_at")
    t.add_argument("--bitflip-dev", type=int, default=0,
                   dest="bitflip_dev")
    t.add_argument("--poison-at", type=int, default=None,
                   dest="poison_at")

    d = sub.add_parser("decode", help="decode-drill child")
    d.add_argument("--dir", required=True)
    d.add_argument("--label", default="c1")
    d.add_argument("--requests", default="0,1,2,3")
    d.add_argument("--max-new", type=int, default=32, dest="max_new")
    d.add_argument("--preempt", action="store_true")
    d.add_argument("--self-sigterm", action="store_true",
                   dest="self_sigterm")

    ro = sub.add_parser("router", help="router-chaos-drill child")
    ro.add_argument("--dir", required=True)
    ro.add_argument("--label", default="c1")
    ro.add_argument("--mode", default="kill",
                    choices=("kill", "wedge", "flap", "deadline_storm",
                             "prefix_storm", "scale_storm", "host_loss",
                             "spec_draft_poison"))
    ro.add_argument("--steady", type=int, default=12)
    ro.add_argument("--requests", type=int, default=8)
    ro.add_argument("--max-new", type=int, default=10, dest="max_new")
    ro.add_argument("--preempt", action="store_true")

    rp = sub.add_parser("replica", help="cross-host replica child "
                                        "(ISSUE 17)")
    rp.add_argument("--dir", required=True)
    rp.add_argument("--label", default="r1")
    rp.add_argument("--ttl", type=float, default=600.0)

    r = sub.add_parser("run", help="orchestrate scenarios")
    r.add_argument("scenarios", nargs="*", default=list(SCENARIOS))
    r.add_argument("--root", default=None)
    r.add_argument("--json", action="store_true")

    a = p.parse_args(argv)
    if a.cmd == "train":
        return _cmd_train(a)
    if a.cmd == "decode":
        return _cmd_decode(a)
    if a.cmd == "router":
        return _cmd_router(a)
    if a.cmd == "replica":
        return _cmd_replica(a)
    import tempfile

    root = a.root or tempfile.mkdtemp(prefix="mxnet-drills-")
    reports = [run_drill(s, root, verbose=not a.json)
               for s in (a.scenarios or SCENARIOS)]
    if a.json:
        print(json.dumps(reports, default=str))
    return 0 if all(r["ok"] for r in reports) else 1


if __name__ == "__main__":
    sys.exit(main())
