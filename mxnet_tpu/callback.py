"""Training callbacks (reference ``python/mxnet/callback.py``)."""
from __future__ import annotations

import logging
import math
import time
from collections import namedtuple

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint", "log_train_metric",
           "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


class Speedometer:
    """Log samples/sec every ``frequent`` batches (reference
    callback.py Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    logging.info(msg, param.epoch, count, speed,
                                 "\t".join(f"{n}={v:.6f}"
                                           for n, v in name_value))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar (reference callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s", prog_bar, percents, "%")


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving Block parameters (reference
    callback.py do_checkpoint, adapted to Gluon save_parameters)."""
    period = int(max(1, period))

    def _callback(epoch, net, *args):
        if (epoch + 1) % period == 0:
            fname = f"{prefix}-{epoch + 1:04d}.params"
            net.save_parameters(fname)
            logging.info("Saved checkpoint to \"%s\"", fname)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Periodic metric logging callback (reference log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            logging.info(
                "Iter[%d] Batch[%d] Train-%s", param.epoch, param.nbatch,
                "\t".join(f"{n}={v:.6f}" for n, v in name_value))
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class LogValidationMetricsCallback:
    """Log eval metrics at the end of each epoch (reference callback.py
    LogValidationMetricsCallback)."""

    def __call__(self, param):
        if not getattr(param, "eval_metric", None):
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         getattr(param, "epoch", 0), name, value)
