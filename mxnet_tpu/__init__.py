"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new design (not a port): NDArray storage is jax.Array in HBM via PJRT;
operators are pure-JAX lowerings fused/compiled by XLA; hybridization is
whole-graph jit; data-parallel/collective training rides XLA collectives
over the ICI mesh.  See SURVEY.md for the blueprint distilled from the
reference (apache/incubator-mxnet 2.0-dev).

Usage mirrors the reference:

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x + 1) * 2
    y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import config  # noqa: E402  (no jax dependency; safe first)
from . import telemetry  # noqa: E402  (no jax dependency; the counter
# registry/event bus must exist before every module that declares into it)
from . import faults  # noqa: E402  (no jax dependency; installs any
# MXNET_FAULT_PLAN before the runtime it instruments imports)

if config.get("MXNET_PROFILER_AUTOSTART"):
    # must import eagerly (profiler is otherwise lazy via _LAZY) so
    # collection starts before user code, not at first mx.profiler access
    from . import profiler as _profiler  # noqa: F401

if config.get("MXNET_ENFORCE_DETERMINISM"):
    # Reference semantics: trade speed for bit-reproducibility.  On TPU the
    # levers are sharding-invariant RNG and pinning matmuls to highest
    # precision (rules out nondeterministic reduced-precision fast paths).
    import jax as _jax

    _jax.config.update("jax_threefry_partitionable", True)
    _jax.config.update("jax_default_matmul_precision", "highest")

from .context import (Context, cpu, cpu_pinned, current_context, gpu, num_gpus,
                      num_tpus, tpu)

from . import engine  # noqa: E402
from . import random  # noqa: E402
from . import ndarray  # noqa: E402
from . import ndarray as nd  # noqa: E402
from .ndarray import NDArray  # noqa: E402
from . import autograd  # noqa: E402

# quantized ops register from contrib (which needs the core initialized),
# then reference-name aliases are re-applied to cover them
from .contrib import quantization as _quantization  # noqa: E402
from .ops import ref_aliases as _ref_aliases  # noqa: E402

_ref_aliases.apply()

from .attribute import AttrScope  # noqa: E402  (reference mx.AttrScope)

# subsystems imported lazily on attribute access to keep import light
_LAZY = {
    "sym": ".symbol",
    "model": ".model",
    "symbol": ".symbol",
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "lr_scheduler": ".lr_scheduler",
    "kv": ".kvstore",
    "kvstore": ".kvstore",
    "io": ".io",
    "image": ".image",
    "initializer": ".initializer",
    "init": ".initializer",
    "metric": ".metric",
    "profiler": ".profiler",
    "preemption": ".preemption",
    "drills": ".drills",
    "amp": ".amp",
    "np": ".numpy",
    "npx": ".numpy_extension",
    "parallel": ".parallel",
    "runtime": ".runtime",
    "cached_step": ".cached_step",
    "program_store": ".program_store",
    "sentinel": ".sentinel",
    "serving": ".serving",
    "serving_decode": ".serving_decode",
    "serving_router": ".serving_router",
    "telemetry": ".telemetry",
    "test_utils": ".test_utils",
    "recordio": ".recordio",
    "util": ".util",
    "executor": ".executor",
    "callback": ".callback",
    "contrib": ".contrib",
    "visualization": ".visualization",
    "viz": ".visualization",
    "library": ".library",
    "config": ".config",
    "operator": ".operator",
    "error": ".error",
    "log": ".log",
    "name": ".name",
    "attribute": ".attribute",
    "dlpack": ".dlpack",
    "registry": ".registry",
    "libinfo": ".libinfo",
    "rtc": ".rtc",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute '{name}'")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
