"""Training-integrity sentinel: in-program state digests, cross-replica
corruption voting, anomaly-windowed rollback, suspect-device quarantine.

PR 11 proved the stack survives being *killed*; nothing defended a job
that keeps running with *wrong bits* — a flipped mantissa in a parameter
replica, a mis-executing chip, a loss quietly diverging.  On large TPU
fleets that silent mode dominates: the job looks healthy while it burns
pod-days training garbage.  This module closes it with one invariant —
**training state is continuously attested** — threaded through the
compiled step, the mesh, the elastic loop, checkpoints, and telemetry:

1. **In-program state digests.**  Every ``MXNET_SENTINEL_EVERY``
   (default 20) steps the donated compiled :class:`~..cached_step.
   TrainStep` program additionally emits a cheap on-device fingerprint
   of the post-update parameters + optimizer state + gradient norm:
   a position-weighted bitcast fold (:func:`fold_leaves` — exact uint32
   arithmetic, so it is bit-deterministic, order-independent across
   mesh shapes, and flips on ANY single-bit perturbation) plus float
   sum / grad-norm signals.  The fingerprint rides a ``lax.cond``
   inside the ONE dispatch — 0 extra dispatches, 0 retraces, and
   non-sentinel steps never execute the fold branch.  The host read is
   deferred exactly like the PR-5 AMP gate: the pending digest is
   consumed when the NEXT sentinel dispatch is offered (its program
   retired long ago, so the read never stalls the current step) or at
   a checkpoint boundary (:meth:`Sentinel.flush`, called by
   ``run_elastic`` BEFORE every save so tainted state is never
   checkpointed).

2. **Cross-replica corruption vote.**  Under ``kvstore='tpu'`` the
   replicated parameters must be bit-identical on every mesh device,
   and the SPMD partitioner computes the replicated fold redundantly
   per device — so the digest output's ``addressable_shards`` carry
   one independently-computed fingerprint per physical replica.  On a
   sentinel read the shards vote: a minority device is *localized*
   (named in a ``corruption`` telemetry event + counted in
   ``sentinel.replica_divergence``), not merely detected.

   FSDP composition (ISSUE 18): the fold itself is mesh-shape
   INVARIANT — exact wrap-around uint32 arithmetic is associative and
   commutative, so a dp×fsdp-sharded state digests to the same integer
   as its replicated or single-chip placement (a scale event or
   topology change never fakes a verdict).  The vote, however, runs on
   the digest's post-reduce output shards, which the partitioner makes
   identical across devices — under fsdp the per-device redundancy
   that powers minority LOCALIZATION degrades to a trivially unanimous
   vote.  Detection (host-recompute mismatch, anomaly windows,
   rollback, quarantine) is unchanged; only the "which chip" attribution
   narrows to the replicated-param case.

3. **Anomaly windows + rollback.**  :class:`Window` generalizes
   ``nonfinite_anomaly`` into an EMA + z-score detector
   (``MXNET_SENTINEL_ZMAX``) over the digest's grad-norm (and any loss
   series the loop feeds via :meth:`Sentinel.observe_loss`).  A tripped
   window — or a corruption vote — makes the :class:`Sentinel` (used as
   ``run_elastic(anomaly_fn=...)``) return True, driving the EXISTING
   anomaly/rollback path under the new fault site ``sentinel.rollback``:
   restore the last digest-verified checkpoint, bit-exact replay, 0
   fresh compiles on a warm cache.

4. **Suspect-device quarantine.**  A corrupt replica (or a
   ``HeartbeatMonitor``-suspected dead rank, fed by the KVStore barrier
   deadline) lands in a persisted :class:`Quarantine` list
   (``<ckpt>/quarantine.json``, written under fault site
   ``sentinel.quarantine``).  ``parallel.spmd.resolve_mesh`` consults
   the active quarantine, so the next restart re-resolves the mesh
   *without* the suspect device — the PR-11 topology-change machinery
   (``restore(like=)`` re-placement), now triggered automatically.

Overhead is a measured number, not a hope: ``benchmark/elastic_drill.py``
A/Bs step time at cadence 20 vs off and bench.py's ``elastic`` lane
stamps ``sentinel_overhead_pct`` (acceptance: < 1% on the train lane).
``mxnet_tpu/drills.py`` runs the end-to-end ``bitflip_param`` and
``loss_spike`` scenarios under ``tools/check_recovery_budget.py``.
"""
from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as onp

from . import config as _config
from . import engine as _engine
from . import faults as _faults
from . import telemetry as _telemetry
from .log import get_logger

__all__ = ["fold_leaves", "tree_digest", "Window", "Quarantine",
           "Sentinel", "install_quarantine", "active_quarantine",
           "quarantine_ranks"]

_LOG = get_logger("mxnet_tpu.sentinel")

_DIGESTS = _telemetry.counter(
    "sentinel.digests",
    "in-program state digests read on host (one per sentinel cadence "
    "step; the deferred read consumes the PREVIOUS sentinel dispatch's "
    "fingerprint, or the pending one at a checkpoint boundary)")
_DIVERGENCE = _telemetry.counter(
    "sentinel.replica_divergence",
    "sentinel reads whose per-replica digest shards disagreed — the "
    "replicated parameters are no longer bit-identical across the mesh "
    "(a corrupt device replica, localized by the vote and named in a "
    "'corruption' event)")
_ROLLBACKS = _telemetry.counter(
    "sentinel.rollbacks",
    "sentinel verdicts that triggered the run_elastic rollback path "
    "(corruption vote or windowed loss/grad-norm anomaly) under fault "
    "site sentinel.rollback")


def _quarantined_entries() -> int:
    q = active_quarantine()
    return len(q.entries()) if q is not None else 0


_telemetry.gauge_fn(
    "sentinel.quarantined", _quarantined_entries,
    "entries (suspect devices + ranks) in the active persisted "
    "quarantine list mesh resolution excludes on restart")


# ---------------------------------------------------------------------------
# digest math (traced: runs INSIDE the compiled step program)
# ---------------------------------------------------------------------------

# FNV-1a primes reused as the leaf combiner; the per-element weights use
# Knuth's multiplicative-hash constant so a permutation of elements (not
# just a value change) moves the fold
_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_ELEM_WEIGHT = 2654435761


def _fold_leaf(x) -> "jnp.ndarray":
    """Position-weighted uint32 fold of one array: exact integer
    arithmetic (wrap-around sum is associative + commutative, so the
    value is independent of XLA reduction order and of the mesh shape a
    replicated leaf is placed on), and any single-bit flip of any
    element changes it."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.floating):
        if x.dtype != jnp.float32:
            # bf16/f16 embed exactly into f32, so a flipped source bit
            # still lands in the bitcast
            x = x.astype(jnp.float32)
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype == jnp.bool_:
        bits = x.astype(jnp.uint32)
    else:
        bits = x.astype(jnp.uint32)
    bits = bits.ravel()
    n = int(bits.shape[0])
    if n == 0:
        return jnp.uint32(0)
    wgt = (jax.lax.iota(jnp.uint32, n) * jnp.uint32(_ELEM_WEIGHT)
           + jnp.uint32(97))
    return jnp.sum(bits * wgt, dtype=jnp.uint32)


def fold_leaves(leaves: Sequence[Any]) -> "jnp.ndarray":
    """Combine per-leaf folds into one uint32 fingerprint.  The combiner
    is order-DEPENDENT across leaves (FNV-style multiply-xor), so two
    swapped leaves change the digest; within a leaf the weighted sum is
    order-independent (mesh-invariant) but position-sensitive."""
    acc = jnp.uint32(_FNV_OFFSET)
    for leaf in leaves:
        acc = (acc * jnp.uint32(_FNV_PRIME)) ^ _fold_leaf(leaf)
    return acc


_JIT_FOLD = jax.jit(fold_leaves)


def tree_digest(tree: Any) -> int:
    """Host-callable fingerprint of an arbitrary pytree — the SAME fold
    the compiled step emits, so an in-program digest can be cross-checked
    against a host recomputation, and two processes holding bit-identical
    state produce the same integer."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree) if l is not None]
    return int(_JIT_FOLD(leaves))


def program_digest(new_w, state_leaves, grads):
    """The digest tuple the compiled step emits on sentinel steps:
    (uint32 fold over post-update params + optimizer state, float32
    parameter sum, float32 global grad norm).  Traced inside the one
    program — callers wrap it in ``lax.cond`` so non-sentinel steps
    never execute it."""
    leaves = list(new_w) + [l for l in state_leaves if l is not None]
    fold = fold_leaves(leaves)
    psum = jnp.float32(0)
    for w in new_w:
        psum = psum + jnp.sum(w.astype(jnp.float32))
    g2 = jnp.float32(0)
    for g in grads:
        g2 = g2 + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return fold, psum, jnp.sqrt(g2)


def zero_digest():
    """The non-sentinel branch of the in-program ``lax.cond``."""
    return jnp.uint32(0), jnp.float32(0), jnp.float32(0)


# ---------------------------------------------------------------------------
# windowed anomaly detection (the nonfinite_anomaly generalization)
# ---------------------------------------------------------------------------

class Window:
    """EMA + z-score anomaly window over one scalar series.

    ``update(v)`` returns True when ``v`` is non-finite (the classic
    divergence ``nonfinite_anomaly`` caught) or, once ``min_count``
    clean observations seeded the window, when ``|v - ema| >
    zmax * std``.  Anomalous values are NOT absorbed into the window —
    a spike cannot normalize itself."""

    def __init__(self, zmax: Optional[float] = None, decay: float = 0.2,
                 min_count: int = 3):
        self.zmax = float(_config.get("MXNET_SENTINEL_ZMAX")
                          if zmax is None else zmax)
        self.decay = float(decay)
        self.min_count = int(min_count)
        self.reset()

    def reset(self) -> None:
        self.count = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, v: float) -> bool:
        v = float(v)
        if not math.isfinite(v):
            return True
        if self.count >= self.min_count:
            std = math.sqrt(self.var) + 1e-12 + 1e-9 * abs(self.mean)
            if abs(v - self.mean) > self.zmax * std:
                return True
        if self.count == 0:
            self.mean = v
        a = self.decay
        d = v - self.mean
        self.mean += a * d
        self.var = (1.0 - a) * (self.var + a * d * d)
        self.count += 1
        return False


# ---------------------------------------------------------------------------
# quarantine (persisted suspect list; consumed by mesh resolution)
# ---------------------------------------------------------------------------

class Quarantine:
    """Persisted list of suspect devices/ranks.  Entries are dicts
    ``{"kind": "device"|"rank", "id": int, "reason": str}`` in a JSON
    file (atomic replace, written under fault site
    ``sentinel.quarantine``).  A corrupt replica (sentinel vote) and a
    hung host (``HeartbeatMonitor`` via the KVStore barrier deadline)
    land in the SAME list, and ``parallel.spmd.resolve_mesh`` excludes
    both kinds on the next mesh resolve."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._entries: List[Dict[str, Any]] = []
        if path is not None and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                self._entries = [e for e in data
                                 if isinstance(e, dict) and "kind" in e]
            except (OSError, ValueError) as e:
                _LOG.warning("unreadable quarantine list %s (%r); "
                             "starting empty", path, e)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._entries]

    def device_ids(self) -> List[int]:
        return sorted({e["id"] for e in self.entries()
                       if e["kind"] == "device"})

    def ranks(self) -> List[int]:
        return sorted({e["id"] for e in self.entries()
                       if e["kind"] == "rank"})

    def _add(self, kind: str, ident: int, reason: str) -> bool:
        with self._lock:
            for e in self._entries:
                if e["kind"] == kind and e["id"] == ident:
                    return False
            self._entries.append(
                {"kind": kind, "id": int(ident), "reason": reason})
        self._persist()
        _LOG.warning("quarantined %s %d (%s)", kind, ident, reason)
        return True

    def add_device(self, device_id: int, reason: str = "") -> bool:
        return self._add("device", device_id, reason)

    def add_rank(self, rank: int, reason: str = "") -> bool:
        return self._add("rank", rank, reason)

    def suspects_device(self, device) -> bool:
        """True when ``device`` (anything with ``.id`` and
        ``.process_index``) is excluded — quarantined by device id, or
        belonging to a quarantined rank."""
        with self._lock:
            for e in self._entries:
                if e["kind"] == "device" and e["id"] == device.id:
                    return True
                if e["kind"] == "rank" \
                        and e["id"] == getattr(device, "process_index", 0):
                    return True
        return False

    def filter_devices(self, devices: Sequence) -> List:
        """The mesh-resolution filter: devices minus every suspect."""
        return [d for d in devices if not self.suspects_device(d)]

    def _persist(self) -> None:
        if self.path is None:
            return
        _faults.retry_call(self._persist_once, site="sentinel.quarantine")

    def _persist_once(self) -> None:
        with self._lock:
            data = json.dumps(self._entries)
        tmp = f"{self.path}.{os.getpid()}.tmp"
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise


# the process-wide active quarantine mesh resolution consults (installed
# by Sentinel construction, or directly via install_quarantine)
_ACTIVE: List[Optional[Quarantine]] = [None]


def install_quarantine(q: Optional[Quarantine]) -> Optional[Quarantine]:
    """Install (or, with None, clear) the process-wide quarantine list
    ``parallel.spmd.resolve_mesh`` and the barrier-deadline hookup
    consult."""
    _ACTIVE[0] = q
    return q


def active_quarantine() -> Optional[Quarantine]:
    return _ACTIVE[0]


def quarantine_ranks(ranks: Sequence[int], reason: str = "") -> int:
    """Feed suspected-dead ranks (a ``HeartbeatMonitor`` verdict from
    the KVStore barrier deadline) into the active quarantine — a hung
    host and a corrupt host converge on one restart-time exclusion
    mechanism.  No-op (returns 0) when no quarantine is installed."""
    q = active_quarantine()
    if q is None:
        return 0
    added = 0
    for r in ranks:
        if q.add_rank(int(r), reason or "suspected dead"):
            added += 1
    return added


# ---------------------------------------------------------------------------
# the sentinel
# ---------------------------------------------------------------------------

class Sentinel:
    """The training-integrity monitor — attach to a compiled
    :class:`~..cached_step.TrainStep` and pass as
    ``run_elastic(anomaly_fn=...)``::

        step = trainer.compile_step(net, loss_fn)
        snt = sentinel.Sentinel(step=step, directory=ckpt_dir)
        run_elastic(step_fn, state, inputs, ckpt, anomaly_fn=snt, ...)

    Per compiled dispatch the step asks :meth:`want_digest` (True every
    ``every`` calls) and hands the emitted device digest to
    :meth:`offer`; ``offer`` first consumes the PREVIOUS pending digest
    (deferred read — that program retired a full cadence ago), votes
    the per-replica shards, updates the anomaly windows, and latches a
    verdict.  ``run_elastic`` reads the verdict via ``__call__`` (the
    anomaly_fn protocol, evaluated on the ``every`` cadence) and via
    :meth:`flush` immediately BEFORE each checkpoint save — so a
    tainted state is never checkpointed and the rollback target is
    always digest-verified.  ``every=0`` (or ``MXNET_SENTINEL_EVERY=0``)
    disables the sentinel entirely."""

    def __init__(self, step=None, directory: Optional[str] = None,
                 every: Optional[int] = None, zmax: Optional[float] = None,
                 strikes: Optional[int] = None,
                 loss_window: bool = True,
                 quarantine: Optional[Quarantine] = None):
        self.every = int(_config.get("MXNET_SENTINEL_EVERY")
                         if every is None else every)
        self.strikes = int(_config.get("MXNET_SENTINEL_STRIKES")
                           if strikes is None else strikes)
        self._gnorm = Window(zmax=zmax)
        self._loss = Window(zmax=zmax) if loss_window else None
        self._calls = 0            # compiled dispatches seen
        self._pending = None       # (fold_arr, psum_arr, gnorm_arr, call)
        self._tripped: Optional[Dict[str, Any]] = None
        self._strike_counts: Dict[int, int] = {}
        self.last_fold: Optional[int] = None
        self.last_gnorm: Optional[float] = None
        self.last_psum: Optional[float] = None
        self.last_vote: Optional[Dict[str, Any]] = None
        self.last_rollback: Optional[Dict[str, Any]] = None
        if quarantine is not None:
            self.quarantine = quarantine
        elif directory is not None:
            self.quarantine = Quarantine(
                os.path.join(directory, "quarantine.json"))
        else:
            self.quarantine = Quarantine(None)
        install_quarantine(self.quarantine)
        if step is not None:
            step.attach_sentinel(self)
        _engine.register_drainable(self)

    # -- TrainStep side ---------------------------------------------------
    def want_digest(self) -> bool:
        """Called once per compiled dispatch; True on sentinel steps."""
        if self.every <= 0:
            return False
        self._calls += 1
        return self._calls % self.every == 0

    def offer(self, fold, psum, gnorm) -> None:
        """Receive the just-dispatched sentinel digest (device arrays,
        unread).  The previously pending digest — whose program retired
        a cadence ago, so the read is lagged and never stalls the
        current step — is consumed first."""
        prev, self._pending = self._pending, (fold, psum, gnorm,
                                              self._calls)
        if prev is not None:
            self._consume(prev)

    def observe_loss(self, value) -> None:
        """Optional: feed an ALREADY-READ host loss value (zero extra
        syncs) into the loss anomaly window."""
        if self._loss is None or self._tripped is not None:
            return
        if self._loss.update(float(value)):
            self._trip("loss_anomaly", value=float(value))

    # -- run_elastic side -------------------------------------------------
    def __call__(self, state=None) -> bool:
        """The ``run_elastic(anomaly_fn=...)`` protocol: True when a
        verdict (corruption vote or windowed anomaly) is latched.  The
        ``sentinel.rollback`` injection site fires here, so a fault
        plan exercises exactly the rollback recovery path."""
        _faults.inject("sentinel.rollback")
        return self._take_verdict()

    def flush(self) -> bool:
        """Consume any pending digest NOW (one blocking read) and
        return the verdict — ``run_elastic`` calls this immediately
        before every checkpoint save, so a state the sentinel rejects
        is never written and every rollback target is attested."""
        pending, self._pending = self._pending, None
        if pending is not None:
            self._consume(pending)
        return self._take_verdict()

    def drain(self) -> None:
        """engine.waitall() hook: consume the pending digest so a
        drained process' verdict/telemetry is complete.  Never raises a
        verdict — the loop (or the next flush) reports it."""
        pending, self._pending = self._pending, None
        if pending is not None:
            try:
                self._consume(pending)
            except Exception as e:      # a drain must never wedge
                _LOG.warning("sentinel drain read failed: %r", e)

    def reset_window(self) -> None:
        """Forget window state + pending digests (rollback landed: the
        restored trajectory re-seeds the EMAs)."""
        self._gnorm.reset()
        if self._loss is not None:
            self._loss.reset()
        self._pending = None

    # -- internals --------------------------------------------------------
    def _take_verdict(self) -> bool:
        tripped, self._tripped = self._tripped, None
        if tripped is None:
            return False
        self.last_rollback = tripped
        _ROLLBACKS.inc()
        _faults.record_event("sentinel.rollback", "rollback", **tripped)
        self.reset_window()
        return True

    def _trip(self, reason: str, **info) -> None:
        if self._tripped is None:
            self._tripped = dict(info, reason=reason)

    def _consume(self, pending) -> None:
        fold, psum, gnorm, _call = pending
        from .ndarray import ndarray as _ndmod

        _ndmod.count_host_sync()
        _DIGESTS.inc()
        # per-replica shard values: under a mesh each device computed
        # the replicated fold REDUNDANTLY from its own physical param
        # replica, so disagreement here IS replica divergence
        shards = sorted(
            ((s.device, int(onp.asarray(s.data).item()))
             for s in fold.addressable_shards),
            key=lambda t: t[0].id)
        values = [v for _d, v in shards]
        counts: Dict[int, int] = {}
        for v in values:
            counts[v] = counts.get(v, 0) + 1
        majority = max(counts, key=lambda v: counts[v])
        self.last_fold = majority
        suspects = [d for d, v in shards if v != majority]
        self.last_vote = {
            "devices": [d.id for d, _v in shards],
            "values": values,
            "majority": majority,
            "suspects": [d.id for d in suspects],
        }
        if suspects:
            _DIVERGENCE.inc()
            by_id = {d.id: v for d, v in shards}
            for dev in suspects:
                n = self._strike_counts.get(dev.id, 0) + 1
                self._strike_counts[dev.id] = n
                _telemetry.event(
                    "corruption", "sentinel", device=dev.id,
                    strikes=n, majority=majority, value=by_id[dev.id])
                if n >= self.strikes:
                    self.quarantine.add_device(
                        dev.id, f"replica divergence x{n} "
                                f"(digest != majority {majority})")
            _LOG.error(
                "cross-replica digest vote: device(s) %s diverged from "
                "majority %d — rolling back to the last verified "
                "checkpoint", [d.id for d in suspects], majority)
            self._trip("replica_divergence",
                       devices=[d.id for d in suspects])
            return
        # clean vote: update the anomaly windows with the float signals
        # (median across shards — replicated post-all-reduce values are
        # normally identical; the median stays sane even if one shard's
        # float path drifted without moving the exact fold)
        g = float(onp.median([onp.asarray(s.data)
                              for s in gnorm.addressable_shards]))
        self.last_gnorm = g
        self.last_psum = float(onp.median(
            [onp.asarray(s.data) for s in psum.addressable_shards]))
        if self._gnorm.update(g):
            self._trip("grad_norm_anomaly", value=g)
