"""Deferred compute: trace imperative NDArray code into a Symbol graph.

Reference analog: ``python/mxnet/_deferred_compute.py`` +
``Imperative::RecordDeferredCompute`` (src/imperative/imperative.cc:296) —
the basis of Gluon 2.0 hybridization.  TPU-native twist: the reference
*defers* execution (records without computing); here ops execute eagerly
(jax async dispatch makes that cheap) while the symbolic node is recorded
alongside — "trace-while-eager", the same trick the autograd tape uses.
``get_symbol`` then reads the recorded graph off the output arrays.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .base import MXNetError

__all__ = ["deferred_compute", "is_deferred_compute", "get_symbol",
           "set_variable"]


class _DCState(threading.local):
    def __init__(self):
        super().__init__()
        self.active = False
        self.counter = 0


_STATE = _DCState()


def is_deferred_compute() -> bool:
    return _STATE.active


is_active = is_deferred_compute


class deferred_compute:
    """Context manager enabling tracing (reference _deferred_compute.py:33)."""

    def __enter__(self):
        self._prev = _STATE.active
        _STATE.active = True
        return self

    def __exit__(self, *exc):
        _STATE.active = self._prev


def set_variable(arr, name: str, shape=None):
    """Mark an NDArray as a named graph input (reference
    MXNDArraySetDeferredComputeVariable)."""
    from .symbol.symbol import SymNode

    node = SymNode(None, name, {}, [])
    arr._dc_sym = (node, 0)


def _auto_var(arr):
    from .symbol.symbol import SymNode

    _STATE.counter += 1
    node = SymNode(None, f"_dc_var{_STATE.counter}", {}, [])
    arr._dc_sym = (node, 0)
    return arr._dc_sym


def record(schema, inputs, attrs, outputs):
    """Called from ndarray.invoke while tracing: attach a SymNode mirroring
    the executed op to the outputs."""
    from . import name as _name_mod
    from .symbol.symbol import SymNode

    in_entries = []
    for a in inputs:
        entry = getattr(a, "_dc_sym", None)
        if entry is None:
            entry = _auto_var(a)
        in_entries.append(entry)
    # same per-thread counter as the symbol API (_apply_op): mixed graphs
    # must never generate colliding auto-names
    node = SymNode(schema.name,
                   _name_mod.current().get(None, schema.name.lower()),
                   dict(attrs), in_entries, max(1, len(outputs)))
    for i, o in enumerate(outputs):
        o._dc_sym = (node, i)


def get_symbol(output_arrays):
    """Extract the traced Symbol for the given outputs (reference
    dc.get_symbol → Imperative::GetDeferredComputeSymbol)."""
    from .symbol.symbol import Symbol

    if not isinstance(output_arrays, (list, tuple)):
        output_arrays = [output_arrays]
    entries = []
    for o in output_arrays:
        entry = getattr(o, "_dc_sym", None)
        if entry is None:
            raise MXNetError(
                "output was not computed inside a deferred_compute scope")
        entries.append(entry)
    return Symbol(entries)
