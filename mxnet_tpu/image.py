"""``mx.image`` — image decode / resize / augmentation.

Reference analog: ``python/mxnet/image/image.py`` (+ C++ augmenters
``src/io/image_aug_default.cc``).  Decode and geometric ops run on host via
OpenCV exactly like the reference; arrays are HWC NDArrays so augmenter
pipelines are drop-in compatible.  ``CreateAugmenter`` mirrors the reference
factory.
"""
from __future__ import annotations

import random as pyrandom
from typing import List, Optional, Tuple

import numpy as onp

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = [
    "imdecode", "imread", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "Augmenter", "SequentialAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "CenterCropAug", "RandomSizedCropAug",
    "HorizontalFlipAug", "CastAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "ColorJitterAug",
    "LightingAug", "ColorNormalizeAug", "RandomGrayAug", "CreateAugmenter",
    "ImageIter",
]


def _cv2():
    import cv2

    return cv2


def _as_host(img):
    return img.asnumpy() if isinstance(img, NDArray) else onp.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs) -> NDArray:
    """Decode an encoded image buffer to HWC NDArray (reference
    image.py imdecode → cv::imdecode)."""
    img = _cv2().imdecode(onp.frombuffer(bytes(buf), onp.uint8), flag)
    if img is None:
        raise MXNetError("imdecode failed: invalid image data")
    if to_rgb and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(onp.ascontiguousarray(img))


def imread(filename, flag=1, to_rgb=True) -> NDArray:
    img = _cv2().imread(filename, flag)
    if img is None:
        raise MXNetError(f"imread failed: {filename}")
    if to_rgb and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(onp.ascontiguousarray(img))


def imresize(src, w, h, interp=1) -> NDArray:
    out = _cv2().resize(_as_host(src), (w, h), interpolation=interp)
    return array(out)


def resize_short(src, size, interp=2) -> NDArray:
    """Resize shorter edge to ``size`` (reference image.py resize_short)."""
    img = _as_host(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(img, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    img = _as_host(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        img = _cv2().resize(img, size, interpolation=interp)
    return array(img)


def random_crop(src, size, interp=2):
    img = _as_host(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(0, w - new_w))
    y0 = pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(img, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    img = _as_host(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(img, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop by area fraction + aspect ratio (reference
    random_size_crop)."""
    img = _as_host(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        ar = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * ar) ** 0.5))
        new_h = int(round((target_area / ar) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(img, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(img, size, interp)


def color_normalize(src, mean, std=None) -> NDArray:
    img = _as_host(src).astype(onp.float32)
    img = img - _as_host(mean)
    if std is not None:
        img = img / _as_host(std)
    return array(img)


# ---------------------------------------------------------------------------
# augmenters (reference image.py Augmenter classes)
# ---------------------------------------------------------------------------


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return array(onp.ascontiguousarray(_as_host(src)[:, ::-1]))
        return src if isinstance(src, NDArray) else array(src)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return array(_as_host(src).astype(self.typ))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return array(_as_host(src).astype(onp.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = onp.array([0.299, 0.587, 0.114], onp.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _as_host(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (img * self._coef).sum(axis=2).mean()
        return array(img * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = onp.array([0.299, 0.587, 0.114], onp.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _as_host(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return array(img * alpha + gray * (1.0 - alpha))


class ColorJitterAug(SequentialAug):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        ts = []
        if brightness:
            ts.append(BrightnessJitterAug(brightness))
        if contrast:
            ts.append(ContrastJitterAug(contrast))
        if saturation:
            ts.append(SaturationJitterAug(saturation))
        pyrandom.shuffle(ts)
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, onp.float32)
        self.eigvec = onp.asarray(eigvec, onp.float32)

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, 3).astype(onp.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return array(_as_host(src).astype(onp.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = onp.asarray(mean, onp.float32)
        self.std = onp.asarray(std, onp.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = onp.array([[0.21, 0.21, 0.21],
                      [0.72, 0.72, 0.72],
                      [0.07, 0.07, 0.07]], onp.float32)

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return array(_as_host(src).astype(onp.float32) @ self._mat)
        return src if isinstance(src, NDArray) else array(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Build the standard augmenter list (reference image.py
    CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4, 4 / 3), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None and onp.asarray(mean).any():
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Augmenting image iterator over .rec or an imglist (reference
    image.py ImageIter — the python-side counterpart of ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, label_width=1, **kwargs):
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._records = None
        self.imglist = None
        if path_imgrec:
            from .recordio import MXIndexedRecordIO, MXRecordIO

            idx = path_imgrec.rsplit(".", 1)[0] + ".idx"
            import os

            if os.path.exists(idx):
                self._records = MXIndexedRecordIO(idx, path_imgrec, "r")
                self._keys = list(self._records.keys)
            else:
                raise MXNetError("ImageIter needs an .idx next to the .rec")
        elif imglist is not None or path_imglist:
            if path_imglist:
                entries = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        labels = onp.array(
                            [float(p) for p in
                             parts[1:1 + label_width]], onp.float32)
                        entries.append((
                            labels[0] if label_width == 1 else labels,
                            parts[-1]))
                self.imglist = entries
            else:
                self.imglist = [
                    (onp.asarray(e[0], onp.float32)
                     if label_width > 1 else float(
                         onp.asarray(e[0]).flat[0]), e[1])
                    for e in imglist]
            self.path_root = path_root
            self._keys = list(range(len(self.imglist)))
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")
        self.shuffle = shuffle
        self.reset()

    def reset(self):
        self._order = list(range(len(self._keys)))
        if self.shuffle:
            pyrandom.shuffle(self._order)
        self.cursor = 0

    def __iter__(self):
        return self

    def next_sample(self):
        if self.cursor >= len(self._order):
            raise StopIteration
        i = self._order[self.cursor]
        self.cursor += 1
        if self._records is not None:
            from .recordio import unpack

            header, img_bytes = unpack(
                self._records.read_idx(self._keys[i]))
            return header.label, img_bytes
        label, fname = self.imglist[i]
        import os

        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, f.read()

    def __next__(self):
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, h, w, c), onp.float32)
        lshape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        batch_label = onp.zeros(lshape, onp.float32)
        i = 0
        while i < self.batch_size:
            label, buf = self.next_sample()
            img = imdecode(buf)
            for aug in self.auglist:
                img = aug(img)
            arr = _as_host(img)
            if arr.shape[:2] != (h, w):
                arr = _cv2().resize(arr, (w, h))
            batch_data[i] = arr
            lab = onp.asarray(label, onp.float32)
            if self.label_width == 1:
                batch_label[i] = lab.flat[0]
            else:
                batch_label[i] = lab.flat[:self.label_width]
            i += 1
        from .io import DataBatch

        nchw = onp.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch([array(nchw)], [array(batch_label)])

    next = __next__
