"""``mx.image`` — image decode / resize / augmentation.

Reference analog: ``python/mxnet/image/image.py`` (+ C++ augmenters
``src/io/image_aug_default.cc``).  Decode and geometric ops run on host via
OpenCV exactly like the reference; arrays are HWC NDArrays so augmenter
pipelines are drop-in compatible.  ``CreateAugmenter`` mirrors the reference
factory.
"""
from __future__ import annotations

import random as pyrandom
from typing import List, Optional, Tuple

import numpy as onp

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = [
    "imdecode", "imread", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "Augmenter", "SequentialAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "CenterCropAug", "RandomSizedCropAug",
    "HorizontalFlipAug", "CastAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "ColorJitterAug",
    "LightingAug", "ColorNormalizeAug", "RandomGrayAug", "CreateAugmenter",
    "ImageIter", "HueJitterAug", "RandomOrderAug", "imrotate",
    "random_rotate", "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
    "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
    "CreateMultiRandCropAugmenter", "CreateDetAugmenter", "ImageDetIter",
]


def _cv2():
    import cv2

    return cv2


def _as_host(img):
    return img.asnumpy() if isinstance(img, NDArray) else onp.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs) -> NDArray:
    """Decode an encoded image buffer to HWC NDArray (reference
    image.py imdecode → cv::imdecode)."""
    img = _cv2().imdecode(onp.frombuffer(bytes(buf), onp.uint8), flag)
    if img is None:
        raise MXNetError("imdecode failed: invalid image data")
    if to_rgb and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(onp.ascontiguousarray(img))


def imread(filename, flag=1, to_rgb=True) -> NDArray:
    img = _cv2().imread(filename, flag)
    if img is None:
        raise MXNetError(f"imread failed: {filename}")
    if to_rgb and img.ndim == 3:
        img = img[:, :, ::-1]
    return array(onp.ascontiguousarray(img))


def imresize(src, w, h, interp=1) -> NDArray:
    out = _cv2().resize(_as_host(src), (w, h), interpolation=interp)
    return array(out)


def resize_short(src, size, interp=2) -> NDArray:
    """Resize shorter edge to ``size`` (reference image.py resize_short)."""
    img = _as_host(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(img, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    img = _as_host(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        img = _cv2().resize(img, size, interpolation=interp)
    return array(img)


def random_crop(src, size, interp=2):
    img = _as_host(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(0, w - new_w))
    y0 = pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(img, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    img = _as_host(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(img, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop by area fraction + aspect ratio (reference
    random_size_crop)."""
    img = _as_host(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        ar = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * ar) ** 0.5))
        new_h = int(round((target_area / ar) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(img, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(img, size, interp)


def color_normalize(src, mean, std=None) -> NDArray:
    img = _as_host(src).astype(onp.float32)
    img = img - _as_host(mean)
    if std is not None:
        img = img / _as_host(std)
    return array(img)


# ---------------------------------------------------------------------------
# augmenters (reference image.py Augmenter classes)
# ---------------------------------------------------------------------------


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return array(onp.ascontiguousarray(_as_host(src)[:, ::-1]))
        return src if isinstance(src, NDArray) else array(src)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return array(_as_host(src).astype(self.typ))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return array(_as_host(src).astype(onp.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = onp.array([0.299, 0.587, 0.114], onp.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _as_host(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (img * self._coef).sum(axis=2).mean()
        return array(img * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = onp.array([0.299, 0.587, 0.114], onp.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _as_host(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return array(img * alpha + gray * (1.0 - alpha))


class ColorJitterAug(SequentialAug):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        ts = []
        if brightness:
            ts.append(BrightnessJitterAug(brightness))
        if contrast:
            ts.append(ContrastJitterAug(contrast))
        if saturation:
            ts.append(SaturationJitterAug(saturation))
        pyrandom.shuffle(ts)
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, onp.float32)
        self.eigvec = onp.asarray(eigvec, onp.float32)

    def __call__(self, src):
        alpha = onp.random.normal(0, self.alphastd, 3).astype(onp.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return array(_as_host(src).astype(onp.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = onp.asarray(mean, onp.float32)
        self.std = onp.asarray(std, onp.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = onp.array([[0.21, 0.21, 0.21],
                      [0.72, 0.72, 0.72],
                      [0.07, 0.07, 0.07]], onp.float32)

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return array(_as_host(src).astype(onp.float32) @ self._mat)
        return src if isinstance(src, NDArray) else array(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Build the standard augmenter list (reference image.py
    CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4, 4 / 3), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None and onp.asarray(mean).any():
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Augmenting image iterator over .rec or an imglist (reference
    image.py ImageIter — the python-side counterpart of ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, label_width=1, **kwargs):
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._records = None
        self.imglist = None
        if path_imgrec:
            from .recordio import MXIndexedRecordIO, MXRecordIO

            idx = path_imgrec.rsplit(".", 1)[0] + ".idx"
            import os

            if os.path.exists(idx):
                self._records = MXIndexedRecordIO(idx, path_imgrec, "r")
                self._keys = list(self._records.keys)
            else:
                raise MXNetError("ImageIter needs an .idx next to the .rec")
        elif imglist is not None or path_imglist:
            if path_imglist:
                entries = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        labels = onp.array(
                            [float(p) for p in
                             parts[1:1 + label_width]], onp.float32)
                        entries.append((
                            labels[0] if label_width == 1 else labels,
                            parts[-1]))
                self.imglist = entries
            else:
                self.imglist = [
                    (onp.asarray(e[0], onp.float32)
                     if label_width > 1 else float(
                         onp.asarray(e[0]).flat[0]), e[1])
                    for e in imglist]
            self.path_root = path_root
            self._keys = list(range(len(self.imglist)))
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")
        self.shuffle = shuffle
        self.reset()

    def reset(self):
        self._order = list(range(len(self._keys)))
        if self.shuffle:
            pyrandom.shuffle(self._order)
        self.cursor = 0

    def __iter__(self):
        return self

    def next_sample(self):
        if self.cursor >= len(self._order):
            raise StopIteration
        i = self._order[self.cursor]
        self.cursor += 1
        if self._records is not None:
            from .recordio import unpack

            header, img_bytes = unpack(
                self._records.read_idx(self._keys[i]))
            return header.label, img_bytes
        label, fname = self.imglist[i]
        import os

        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, f.read()

    def __next__(self):
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, h, w, c), onp.float32)
        lshape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        batch_label = onp.zeros(lshape, onp.float32)
        i = 0
        while i < self.batch_size:
            label, buf = self.next_sample()
            img = imdecode(buf)
            for aug in self.auglist:
                img = aug(img)
            arr = _as_host(img)
            if arr.shape[:2] != (h, w):
                arr = _cv2().resize(arr, (w, h))
            batch_data[i] = arr
            lab = onp.asarray(label, onp.float32)
            if self.label_width == 1:
                batch_label[i] = lab.flat[0]
            else:
                batch_label[i] = lab.flat[:self.label_width]
            i += 1
        from .io import DataBatch

        nchw = onp.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch([array(nchw)], [array(batch_label)])

    next = __next__


# ---------------------------------------------------------------------------
# rotation + remaining classifier augmenters (reference image.py imrotate,
# HueJitterAug, RandomOrderAug)
# ---------------------------------------------------------------------------

def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate about the center (reference image.py imrotate).  zoom_in
    scales so no border shows; zoom_out scales so the full rotated image
    fits."""
    if zoom_in and zoom_out:
        raise ValueError("zoom_in and zoom_out are mutually exclusive")
    cv2 = _cv2()
    img = _as_host(src)
    h, w = img.shape[:2]
    rad = abs(rotation_degrees) * onp.pi / 180.0
    c, s = float(onp.cos(rad)), float(onp.sin(rad))
    scale = 1.0
    if zoom_out:       # fit the whole rotated frame inside (w, h)
        scale = min(w / (w * c + h * s), h / (w * s + h * c))
    elif zoom_in:      # crop away any border: inverse of the zoom_out fit
        scale = 1.0 / min(w / (w * c + h * s), h / (w * s + h * c))
    m = cv2.getRotationMatrix2D((w / 2, h / 2), rotation_degrees, scale)
    out = cv2.warpAffine(img, m, (w, h))
    return array(out) if isinstance(src, NDArray) else out


def random_rotate(src, angle_limits, zoom_in=False, zoom_out=False):
    """Rotate by a uniform random angle in ``angle_limits`` (reference
    image.py random_rotate)."""
    return imrotate(src, pyrandom.uniform(*angle_limits),
                    zoom_in=zoom_in, zoom_out=zoom_out)


class HueJitterAug(Augmenter):
    """Hue jitter in HSV space (reference image.py HueJitterAug)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        cv2 = _cv2()
        img = _as_host(src).astype(onp.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        hsv = cv2.cvtColor(onp.clip(img, 0, 255).astype(onp.uint8),
                           cv2.COLOR_RGB2HSV).astype(onp.float32)
        hsv[..., 0] = (hsv[..., 0] + alpha * 180.0) % 180.0
        out = cv2.cvtColor(hsv.astype(onp.uint8),
                           cv2.COLOR_HSV2RGB).astype(onp.float32)
        return array(out) if isinstance(src, NDArray) else out


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


# ---------------------------------------------------------------------------
# detection augmenters + ImageDetIter (reference image/detection.py).
# Boxes are [N, 5+] rows (class_id, xmin, ymin, xmax, ymax, …) with
# coordinates NORMALIZED to [0, 1] — the reference's det-label convention.
# ---------------------------------------------------------------------------

class DetAugmenter:
    """Detection augmenter base: __call__(img, label) -> (img, label)
    (reference detection.py:40)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline (reference
    detection.py:66) — geometry-preserving augs only."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick ONE child augmenter (or skip entirely with
    ``skip_prob``) per sample (reference detection.py:91)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates with probability p (reference
    detection.py:127)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            img = _as_host(src)
            src = onp.ascontiguousarray(img[:, ::-1])
            label = label.copy()
            x0 = 1.0 - label[:, 3]
            x1 = 1.0 - label[:, 1]
            label[:, 1], label[:, 3] = x0, x1
        return src, label


def _box_overlap_frac(label, crop):
    """Fraction of each box's area inside crop (both normalized corner
    boxes); crop = (x0, y0, x1, y1)."""
    ix0 = onp.maximum(label[:, 1], crop[0])
    iy0 = onp.maximum(label[:, 2], crop[1])
    ix1 = onp.minimum(label[:, 3], crop[2])
    iy1 = onp.minimum(label[:, 4], crop[3])
    inter = onp.clip(ix1 - ix0, 0, None) * onp.clip(iy1 - iy0, 0, None)
    area = (label[:, 3] - label[:, 1]) * (label[:, 4] - label[:, 2])
    return onp.where(area > 0, inter / onp.maximum(area, 1e-12), 0.0)


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference detection.py:153): sample
    crops until every kept object is covered >= min_object_covered; boxes
    are re-expressed in the crop's normalized frame, and objects whose
    center leaves the crop are ejected."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _try_crop(self, label):
        scale = pyrandom.uniform(self.area_range[0],
                                 min(1.0, self.area_range[1]))
        ratio = pyrandom.uniform(*self.aspect_ratio_range)
        cw = min(1.0, (scale * ratio) ** 0.5)
        ch = min(1.0, (scale / ratio) ** 0.5)
        x0 = pyrandom.uniform(0.0, 1.0 - cw)
        y0 = pyrandom.uniform(0.0, 1.0 - ch)
        crop = (x0, y0, x0 + cw, y0 + ch)
        frac = _box_overlap_frac(label, crop)
        keep = frac >= self.min_eject_coverage
        if not keep.any():
            return None
        if (frac[keep] < self.min_object_covered).any():
            return None
        new = label[keep].copy()
        new[:, 1] = (onp.clip(new[:, 1], x0, crop[2]) - x0) / cw
        new[:, 3] = (onp.clip(new[:, 3], x0, crop[2]) - x0) / cw
        new[:, 2] = (onp.clip(new[:, 2], y0, crop[3]) - y0) / ch
        new[:, 4] = (onp.clip(new[:, 4], y0, crop[3]) - y0) / ch
        return crop, new

    def __call__(self, src, label):
        for _ in range(self.max_attempts):
            got = self._try_crop(label)
            if got is None:
                continue
            (x0, y0, x1, y1), new_label = got
            img = _as_host(src)
            h, w = img.shape[:2]
            out = img[int(y0 * h):int(y1 * h), int(x0 * w):int(x1 * w)]
            if out.size == 0:
                continue
            return out, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad onto a larger canvas at a random offset; boxes shrink into the
    new normalized frame (reference detection.py:324)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _as_host(src)
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(max(1.0, self.area_range[0]),
                                     self.area_range[1])
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(w * (scale * ratio) ** 0.5)
            nh = int(h * (scale / ratio) ** 0.5)
            if nw < w or nh < h:
                continue
            off_x = pyrandom.randint(0, nw - w)
            off_y = pyrandom.randint(0, nh - h)
            canvas = onp.empty((nh, nw, img.shape[2]), img.dtype)
            canvas[:] = onp.asarray(self.pad_val, img.dtype)
            canvas[off_y:off_y + h, off_x:off_x + w] = img
            new = label.copy()
            new[:, 1] = (new[:, 1] * w + off_x) / nw
            new[:, 3] = (new[:, 3] * w + off_x) / nw
            new[:, 2] = (new[:, 2] * h + off_y) / nh
            new[:, 4] = (new[:, 4] * h + off_y) / nh
            return canvas, new
        return img, label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """One DetRandomSelectAug over per-threshold croppers (reference
    detection.py:418) — thresholds may be scalars or equal-length lists."""

    covered = min_object_covered if isinstance(min_object_covered, list) \
        else [min_object_covered]
    aspects = aspect_ratio_range if isinstance(aspect_ratio_range[0],
                                               (list, tuple)) \
        else [aspect_ratio_range]
    areas = area_range if isinstance(area_range[0], (list, tuple)) \
        else [area_range]
    eject = min_eject_coverage if isinstance(min_eject_coverage, list) \
        else [min_eject_coverage]
    n = max(len(covered), len(aspects), len(areas), len(eject))

    def pick(lst, i):
        return lst[i % len(lst)]

    crops = [DetRandomCropAug(pick(covered, i), pick(aspects, i),
                              pick(areas, i), pick(eject, i), max_attempts)
             for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter stack (reference detection.py:483)."""
    augs: List[DetAugmenter] = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        augs.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])),
            min_eject_coverage, max_attempts, skip_prob=1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        augs.append(DetRandomSelectAug([pad], skip_prob=1 - rand_pad))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    # force to the network input size LAST so labels stay consistent
    augs.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    augs.append(DetBorrowAug(CastAug()))
    color = []
    if brightness:
        color.append(BrightnessJitterAug(brightness))
    if contrast:
        color.append(ContrastJitterAug(contrast))
    if saturation:
        color.append(SaturationJitterAug(saturation))
    if hue:
        color.append(HueJitterAug(hue))
    if color:
        augs.append(DetBorrowAug(RandomOrderAug(color)))
    if pca_noise > 0:
        augs.append(DetBorrowAug(LightingAug(pca_noise)))
    if rand_gray > 0:
        augs.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53], onp.float32)
    if std is True:
        std = onp.array([58.395, 57.12, 57.375], onp.float32)
    if mean is not None or std is not None:
        mean = onp.zeros(3, onp.float32) if mean is None \
            else onp.asarray(mean, onp.float32)
        std = onp.ones(3, onp.float32) if std is None \
            else onp.asarray(std, onp.float32)
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator (reference detection.py:625): labels are the
    reference det format — per image ``[header_width, obj_width,
    (extra header...), (id, xmin, ymin, xmax, ymax, ...) * N]`` with
    normalized coords.  Batches pad object counts with -1 rows."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        # det labels are variable-width: read any .lst ONCE at full width
        # here and hand the parsed list down (ImageIter's in-memory-list
        # path only re-wraps it, no second file parse)
        if path_imglist:
            with open(path_imglist) as f:
                imglist = [
                    [onp.asarray([float(p) for p in parts[1:-1]],
                                 onp.float32), parts[-1]]
                    for parts in (line.strip().split("\t") for line in f)
                    if len(parts) >= 2]
            path_imglist = None
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imglist=None, path_root=path_root,
                         shuffle=shuffle, aug_list=[],
                         imglist=imglist, label_width=1)
        self.auglist = aug_list
        # restore FULL label width (ImageIter narrowed in-memory labels
        # to label_width scalars)
        if imglist is not None:
            self.imglist = [(onp.asarray(e[0], onp.float32).ravel(), e[-1])
                            for e in imglist]

    @staticmethod
    def _parse_label(raw):
        """Flat det label -> [N, obj_width] float array (id, x0, y0, x1,
        y1, ...)."""
        raw = onp.asarray(raw, onp.float32).ravel()
        if raw.size < 2:
            raise MXNetError("det label must carry header+object widths")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError("det object width must be >= 5")
        body = raw[header_width:]
        n = body.size // obj_width
        return body[:n * obj_width].reshape(n, obj_width)

    def next_sample(self):
        label, buf = super().next_sample()
        return self._parse_label(label), buf

    def __next__(self):
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, h, w, c), onp.float32)
        rows = []
        i = 0
        while i < self.batch_size:
            label, buf = self.next_sample()
            img = imdecode(buf)
            img = _as_host(img)
            for aug in self.auglist:
                img, label = aug(img, label)
            arr = _as_host(img)
            if arr.shape[:2] != (h, w):
                arr = _cv2().resize(arr, (w, h))
            batch_data[i] = arr
            rows.append(label)
            i += 1
        maxn = max(len(r) for r in rows)
        obj_w = rows[0].shape[1]
        batch_label = onp.full((self.batch_size, max(maxn, 1), obj_w),
                               -1.0, onp.float32)
        for i, r in enumerate(rows):
            if len(r):
                batch_label[i, :len(r)] = r
        from .io import DataBatch

        nchw = onp.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch([array(nchw)], [array(batch_label)])

    next = __next__
