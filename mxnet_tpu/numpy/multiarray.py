"""``mx.np.ndarray`` — the NumPy-semantics array.

Reference analog: ``python/mxnet/numpy/multiarray.py`` (~10k LoC of
hand-written wrappers over ``_npi_*`` C++ ops).  TPU-native design: the
array *is* an :class:`mxnet_tpu.ndarray.NDArray` subclass (same jax.Array
storage, same tape) and the operator surface is *generated* by delegating
straight to ``jax.numpy`` — which already implements NumPy semantics as XLA
lowerings — through one autograd-aware dispatcher (:func:`apply_np`).
Reference ops like ``_npi_add`` (src/api/operator/) become direct jnp calls;
there is nothing to port because XLA is the kernel library.
"""
from __future__ import annotations

import numbers
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import autograd
from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, _wrap
from ..util import is_np_default_dtype

__all__ = ["ndarray", "apply_np", "array", "asarray", "from_nd", "default_dtype"]


def default_dtype():
    return onp.float64 if is_np_default_dtype() else onp.float32


# ---------------------------------------------------------------------------
# generic autograd-aware dispatch over arbitrary jnp callables
# ---------------------------------------------------------------------------


def _collect(obj, leaves):
    """Replace NDArray leaves in a nested (tuple/list/dict) structure with
    positional placeholders; return a rebuildable spec."""
    if isinstance(obj, NDArray):
        leaves.append(obj)
        return ("_leaf_", len(leaves) - 1)
    if isinstance(obj, (tuple, list)):
        return type(obj)(_collect(o, leaves) for o in obj)
    if isinstance(obj, dict):
        return {k: _collect(v, leaves) for k, v in obj.items()}
    return obj


def _rebuild(spec, arrays):
    if isinstance(spec, tuple) and len(spec) == 2 and spec[0] == "_leaf_":
        return arrays[spec[1]]
    if isinstance(spec, (tuple, list)):
        return type(spec)(_rebuild(s, arrays) for s in spec)
    if isinstance(spec, dict):
        return {k: _rebuild(v, arrays) for k, v in spec.items()}
    return spec


def _wrap_out(obj, ctx, cls):
    if isinstance(obj, jax.Array):
        return _wrap(obj, ctx, cls)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
        return type(obj)(*(_wrap_out(o, ctx, cls) for o in obj))
    if isinstance(obj, (tuple, list)):
        return type(obj)(_wrap_out(o, ctx, cls) for o in obj)
    return obj


def _out_leaves(obj, acc):
    if isinstance(obj, NDArray):
        acc.append(obj)
    elif isinstance(obj, (tuple, list)):
        for o in obj:
            _out_leaves(o, acc)


# -- symbolic tracing support ------------------------------------------------
# np ops carry a jnp function, not a registry op; for deferred-compute
# tracing they all record through ONE registered op, `_np_call`, whose attrs
# (jnp function name + arg-structure spec) re-create the call at graph
# execution / after JSON round-trip.
def _resolve_jnp(name: str):
    if name.startswith("linalg."):
        return getattr(jnp.linalg, name[len("linalg."):], None)
    fn = getattr(jnp, name, None)
    if fn is not None:
        return fn
    import jax.nn as jnn
    import jax.scipy.special as jsp

    return getattr(jnn, name, None) or getattr(jsp, name, None)


def _np_call(arrays, jnp_name=None, spec=None):
    jfn = _resolve_jnp(jnp_name)
    if jfn is None:
        raise MXNetError(f"_np_call: cannot resolve jnp function {jnp_name!r}")
    a, k = _rebuild(spec, list(arrays))
    return jfn(*a, **k)


from ..ops.registry import find_op as _find_op, register as _register  # noqa: E402

if _find_op("_np_call") is None:
    _register("_np_call", num_inputs=-1, num_outputs=-1,
              namespaces=[])(_np_call)


def apply_np(jfn, name, args, kwargs, cls=None):
    """Run a jax.numpy callable over mx arrays with tape recording.

    The analog of ``MXImperativeInvokeImpl`` for the np namespace: unwraps
    arrays wherever they sit in args/kwargs, runs under ``jax.vjp`` while
    autograd records, wraps outputs as :class:`ndarray`.
    """
    leaves: list = []
    spec = _collect((tuple(args), dict(kwargs)), leaves)
    ctx = leaves[0]._ctx if leaves else current_context()
    cls = cls or (type(leaves[0]) if leaves and type(leaves[0]) is not NDArray
                  else ndarray)
    arrays = [l._data for l in leaves]

    def fn(*arrs):
        a, k = _rebuild(spec, list(arrs))
        return jfn(*a, **k)

    record = autograd.is_recording() and len(leaves) > 0
    if record:
        try:
            raw, vjp_fn = jax.vjp(fn, *arrays)
        except (TypeError, jax.errors.JaxRuntimeError):
            record = False
            raw = fn(*arrays)
    else:
        raw = fn(*arrays)

    out = _wrap_out(raw, ctx, cls)
    if record:
        outs: list = []
        _out_leaves(out, outs)
        if outs:
            def flat_fn(*arrs, _fn=fn):
                # replayable pure fn: flatten any nested output structure
                # into the same leaf order the tape records
                import jax as _jax

                return tuple(_jax.tree_util.tree_leaves(_fn(*arrs)))

            node = autograd.TapeNode(
                vjp_fn, leaves, len(outs),
                [o.shape for o in outs], [o._data.dtype for o in outs],
                name=name, fn=flat_fn, input_vals=list(arrays))
            # vjp_fn returns cotangents for *all* leaves given cotangents for
            # the full raw output structure; reshape through a shim so slots
            # line up when the output is a tuple
            if isinstance(raw, (tuple, list)):

                def tuple_vjp(cts):
                    cts = list(cts) if isinstance(cts, (tuple, list)) else [cts]
                    if hasattr(raw, "_fields"):  # NamedTuple (qr/svd/slogdet)
                        return vjp_fn(type(raw)(*cts))
                    return vjp_fn(type(raw)(cts))

                node.vjp_fn = tuple_vjp
            for i, o in enumerate(outs):
                o._ag_node = node
                o._ag_out_index = i

    from .. import _deferred_compute as _dc

    if _dc.is_active() and leaves and _resolve_jnp(name) is not None:
        outs = []
        _out_leaves(out, outs)
        if outs:
            _dc.record(_find_op("_np_call"), leaves,
                       {"jnp_name": name, "spec": spec}, outs)
    return out


class ndarray(NDArray):
    """NumPy-semantics array on a device (reference mx.np.ndarray)."""

    __slots__ = ()

    # -- NumPy dispatch protocol (reference numpy_dispatch_protocol.py:
    # onp.mean(mx_array) etc. stay in the mx world instead of silently
    # coercing to host numpy through __array__) ---------------------------
    def __array_function__(self, func, types, args, kwargs):
        import mxnet_tpu.numpy as _mnp

        # submodule-qualified APIs (numpy.linalg.*, numpy.fft.* …)
        # resolve against the matching device submodule
        mod = getattr(func, "__module__", "") or ""
        ns = _mnp
        if mod.startswith("numpy.") and "." in mod:
            ns = getattr(_mnp, mod.split(".", 1)[1].split(".")[0], _mnp)
        target = getattr(ns, func.__name__, None)
        if target is None and ns is not _mnp:
            target = getattr(_mnp, func.__name__, None)
        if target is None or not callable(target):
            return NotImplemented
        return target(*args, **kwargs)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        def _host(v):
            return onp.asarray(v) if isinstance(v, NDArray) else v

        if method != "__call__" or kwargs.get("out") is not None:
            # host-side path (in-place out=, .reduce/.accumulate/...):
            # coerce mx arrays via __array__ so e.g. `host += mx_arr`
            # keeps working as it did before this protocol existed
            out = kwargs.get("out")
            if out is not None and any(isinstance(o, NDArray)
                                       for o in (out if isinstance(
                                           out, tuple) else (out,))):
                return NotImplemented  # can't write into a device array
            return getattr(ufunc, method)(
                *(_host(i) for i in inputs),
                **{k: _host(v) for k, v in kwargs.items()})
        if kwargs:
            # dtype=/where=/casting= and friends aren't part of the device
            # fns' signatures — compute on host via __array__
            return getattr(ufunc, method)(*(_host(i) for i in inputs),
                                          **kwargs)
        import mxnet_tpu.numpy as _mnp

        target = getattr(_mnp, ufunc.__name__, None)
        if target is None or not callable(target):
            return getattr(ufunc, method)(*(_host(i) for i in inputs))
        # promote host-numpy operands so mixed `host_arr * mx_arr`
        # expressions dispatch on-device regardless of operand order
        promoted = [
            _mnp.array(i) if isinstance(i, onp.ndarray) and i.ndim > 0
            else i
            for i in inputs]
        return target(*promoted)

    # -- numpy-flavored overrides ---------------------------------------
    def reshape(self, *shape, order="C", **kwargs):
        if order != "C":
            raise NotImplementedError("only order='C' reshape is supported")
        if "newshape" in kwargs:
            shape = kwargs["newshape"]
        elif "shape" in kwargs:
            shape = kwargs["shape"]
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        elif len(shape) == 1 and isinstance(shape[0], int):
            shape = (shape[0],)
        return apply_np(jnp.reshape, "reshape", (self, tuple(shape)), {})

    def flatten(self, order="C"):
        if order != "C":
            raise NotImplementedError("only order='C' flatten is supported")
        return apply_np(jnp.ravel, "ravel", (self,), {})

    def ravel(self, order="C"):
        if order != "C":
            raise NotImplementedError("only order='C' ravel is supported")
        return apply_np(jnp.ravel, "ravel", (self,), {})

    def std(self, axis=None, ddof=0, keepdims=False):
        return apply_np(jnp.std, "std", (self,),
                        {"axis": axis, "ddof": ddof, "keepdims": keepdims})

    def var(self, axis=None, ddof=0, keepdims=False):
        return apply_np(jnp.var, "var", (self,),
                        {"axis": axis, "ddof": ddof, "keepdims": keepdims})

    def cumsum(self, axis=None, dtype=None):
        return apply_np(jnp.cumsum, "cumsum", (self,),
                        {"axis": axis, "dtype": dtype})

    def any(self, axis=None, keepdims=False):
        return apply_np(jnp.any, "any", (self,),
                        {"axis": axis, "keepdims": keepdims})

    def all(self, axis=None, keepdims=False):
        return apply_np(jnp.all, "all", (self,),
                        {"axis": axis, "keepdims": keepdims})

    def round(self, decimals=0):
        return apply_np(jnp.round, "round", (self,), {"decimals": decimals})

    def nonzero(self):
        return tuple(from_nd_raw(a, self._ctx) for a in onp.nonzero(self.asnumpy()))

    def tolist(self):
        return self.asnumpy().tolist()

    def copy(self):
        return _wrap(self._data, self._ctx, type(self))

    def astype(self, dtype, copy=True):
        from ..ndarray.ndarray import _dtype_np

        dt = _dtype_np(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return apply_np(jnp.asarray, "astype", (self,), {"dtype": dt})

    def item(self, *args):
        return self.asnumpy().item(*args)

    @property
    def device(self):
        return self._ctx

    def to_device(self, device):
        return self.as_in_context(device)

    # numpy repr
    def __repr__(self):
        try:
            body = repr(self.asnumpy()).replace("array", "array", 1)
        except MXNetError as e:
            body = f"<error: {e}>"
        return body

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, key):
        from ..ndarray.ndarray import _index_unwrap

        key = _index_unwrap(key)
        return apply_np(lambda a: a[key], "getitem", (self,), {})

    # np comparisons yield bool arrays (nd legacy yields float 0/1)
    def __eq__(self, other):
        if other is None:
            return False
        return apply_np(jnp.equal, "equal", (self, other), {})

    def __ne__(self, other):
        if other is None:
            return True
        return apply_np(jnp.not_equal, "not_equal", (self, other), {})

    def __gt__(self, other):
        return apply_np(jnp.greater, "greater", (self, other), {})

    def __ge__(self, other):
        return apply_np(jnp.greater_equal, "greater_equal", (self, other), {})

    def __lt__(self, other):
        return apply_np(jnp.less, "less", (self, other), {})

    def __le__(self, other):
        return apply_np(jnp.less_equal, "less_equal", (self, other), {})

    __hash__ = None

    def dot(self, other):
        return apply_np(jnp.dot, "dot", (self, other), {})

    def __matmul__(self, other):
        return apply_np(jnp.matmul, "matmul", (self, other), {})

    @property
    def T(self):
        return apply_np(jnp.transpose, "transpose", (self,), {})


def from_nd(arr: NDArray) -> ndarray:
    """View an mx.nd.NDArray as mx.np.ndarray (shares storage + tape)."""
    return arr.as_np_ndarray()


def from_nd_raw(data, ctx) -> ndarray:
    return _wrap(jnp.asarray(data), ctx, ndarray)


def array(obj, dtype=None, ctx: Optional[Context] = None, device=None,
          copy=True) -> ndarray:
    """Create an mx.np array (reference multiarray.array).

    Default dtype follows MXNet-np rules: float64 input narrows to float32
    unless ``util.set_np(dtype=True)`` is active; ints/bools pass through.
    """
    ctx = device or ctx or current_context()
    if isinstance(obj, NDArray):
        from ..util import x64_creation_scope

        data = obj._data
        if dtype is not None:
            with x64_creation_scope(dtype, ctx):
                data = data.astype(dtype)
                data = jax.device_put(data, ctx.jax_device)
            return _wrap(data, ctx, ndarray)
        return _wrap(jax.device_put(data, ctx.jax_device), ctx, ndarray)
    np_in = onp.asarray(obj)
    if dtype is None:
        if np_in.dtype == onp.float64 and not is_np_default_dtype():
            dtype = onp.float32
        else:
            dtype = np_in.dtype
    from ..ndarray.ndarray import _dtype_np

    want = _dtype_np(dtype)
    # honest 64-bit values on the CPU backend (policy: x64_creation_scope);
    # accelerators keep x32 narrowing
    from ..util import x64_creation_scope

    with x64_creation_scope(want, ctx):
        data = jax.device_put(jnp.asarray(np_in, want), ctx.jax_device)
    return _wrap(data, ctx, ndarray)


def asarray(obj, dtype=None, ctx=None) -> ndarray:
    if isinstance(obj, ndarray) and dtype is None:
        return obj
    return array(obj, dtype=dtype, ctx=ctx)
