"""``mx.np.linalg`` — NumPy linalg over XLA.

Reference analog: ``src/operator/numpy/linalg/`` (eig/svd/solve/… custom
CUDA+LAPACK kernels, ~8k LoC).  On TPU these are XLA's native decompositions
via ``jax.numpy.linalg`` — nothing to hand-write.
"""
from __future__ import annotations

import sys as _sys

import jax.numpy as _jnp

from .multiarray import apply_np

_this = _sys.modules[__name__]

_FUNCS = [
    "norm", "svd", "svdvals", "inv", "pinv", "det", "slogdet", "eig",
    "eigh", "eigvals", "eigvalsh", "cholesky", "qr", "solve", "lstsq",
    "matrix_rank", "matrix_power", "matrix_norm", "vector_norm",
    "tensorinv", "tensorsolve", "multi_dot", "cond", "matrix_transpose",
    "outer", "cross", "diagonal", "trace", "vecdot",
]


def _make(name):
    jfn = getattr(_jnp.linalg, name)

    def fn(*args, **kwargs):
        return apply_np(jfn, f"linalg.{name}", args, kwargs)

    fn.__name__ = name
    return fn


for _name in _FUNCS:
    if hasattr(_jnp.linalg, _name):
        setattr(_this, _name, _make(_name))

__all__ = [n for n in _FUNCS if hasattr(_this, n)]
