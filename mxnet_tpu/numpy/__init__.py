"""``mx.np`` — NumPy-compatible array API on TPU.

Reference analog: ``python/mxnet/numpy/`` (~42k LoC of ``_npi_*`` operator
wrappers, `multiarray.py`, dispatch/fallback protocol modules).  Here the
whole surface is generated over ``jax.numpy`` through one autograd-aware
dispatcher (:func:`.multiarray.apply_np`); names jnp lacks fall back to host
NumPy (the reference's ``numpy_op_fallback.py`` idea).
"""
from __future__ import annotations

import sys as _sys
import types as _types

import jax as _jax
import jax.numpy as _jnp
import numpy as _onp

from ..context import current_context as _current_context
from ..ndarray.ndarray import NDArray as _NDArray, _wrap as _wrap_arr
from .multiarray import (apply_np, array, asarray, default_dtype, from_nd,
                         ndarray)

_this = _sys.modules[__name__]

# --- dtypes & constants ----------------------------------------------------
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = _jnp.bfloat16
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma
dtype = _onp.dtype
integer = _onp.integer
floating = _onp.floating

# --- generated jnp-delegating function surface -----------------------------
# Each name maps 1:1 onto a jax.numpy callable; arrays anywhere in the
# args/kwargs are unwrapped, outputs wrapped, and the call recorded on the
# autograd tape when recording (reference generates these per-op from the
# C++ registry; see python/mxnet/numpy/multiarray.py and src/api/operator/).
_JNP_FUNCS = [
    # manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays",
    "concatenate", "stack", "vstack", "hstack", "dstack", "column_stack",
    "row_stack", "split", "array_split", "hsplit", "vsplit", "dsplit",
    "tile", "repeat", "roll", "rot90", "flip", "fliplr", "flipud",
    "append", "pad", "trim_zeros", "atleast_1d", "atleast_2d", "atleast_3d",
    # search/sort/unique
    "unique", "sort", "argsort", "searchsorted", "where", "take",
    "take_along_axis", "clip", "diag", "diagonal", "diagflat", "trace",
    "tril", "triu", "extract", "flatnonzero", "argwhere", "nonzero",
    "count_nonzero", "partition", "argpartition", "lexsort",
    # elementwise math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "divmod", "power", "negative", "positive",
    "absolute", "abs", "fabs", "sign", "floor", "ceil",
    "trunc", "around", "round", "rint", "exp", "expm1", "exp2", "log", "log2",
    "log10", "log1p", "sqrt", "cbrt", "square", "reciprocal",
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "arctan2",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "degrees", "radians", "deg2rad", "rad2deg", "hypot",
    "maximum", "minimum", "fmax", "fmin", "heaviside", "copysign",
    "ldexp", "frexp", "logaddexp", "logaddexp2", "gcd", "lcm", "interp",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift", "sinc", "i0", "nan_to_num", "real", "imag",
    "conjugate", "conj", "angle",
    # linear algebra
    "matmul", "dot", "vdot", "inner", "outer", "tensordot", "einsum",
    "kron", "cross", "convolve", "correlate",
    # reductions & statistics
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax",
    "argmin", "argmax", "ptp", "median", "percentile", "quantile",
    "average", "nansum", "nanprod", "nanmean", "nanstd", "nanvar",
    "nanmin", "nanmax", "nanargmin", "nanargmax", "nanmedian",
    "nanpercentile", "nanquantile", "cumsum", "cumprod", "nancumsum",
    "nancumprod", "all", "any", "diff", "ediff1d", "gradient",
    "histogram", "histogram2d", "histogram_bin_edges", "bincount",
    "digitize", "corrcoef", "cov",
    # logic
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "isnan", "isinf", "isfinite", "isneginf", "isposinf", "isclose",
    "allclose", "array_equal", "array_equiv", "signbit", "iscomplexobj",
    "isrealobj", "isreal", "iscomplex",
    # sets
    "intersect1d", "union1d", "setdiff1d", "setxor1d", "in1d", "isin",
    # polynomials / misc
    "polyval", "polyadd", "polysub", "polymul", "polyder", "polyint",
    "vander", "unwrap", "unravel_index", "ravel_multi_index",
    "apply_along_axis", "piecewise", "select", "choose", "compress",
    "resize",
    "meshgrid", "indices", "tril_indices", "triu_indices", "diag_indices",
    "result_type", "promote_types", "can_cast", "shape", "ndim", "size",
    "iterable", "isscalar",
]


def _make_fn(name):
    jfn = getattr(_jnp, name)

    def fn(*args, **kwargs):
        return apply_np(jfn, name, args, kwargs)

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = (jfn.__doc__ or "") and (
        f"mx.np.{name} — NumPy-semantics op lowered via jax.numpy.{name}.\n\n"
        + (jfn.__doc__ or ""))
    return fn


for _name in _JNP_FUNCS:
    if hasattr(_jnp, _name) and not hasattr(_this, _name):
        setattr(_this, _name, _make_fn(_name))


# --- creation functions (need ctx/device handling) -------------------------
def _create(jfn, args, kwargs, dtype=None, ctx=None):
    ctx = ctx or _current_context()
    # honest 64-bit values on backends that hold them (policy + rationale:
    # util.x64_creation_scope); accelerator ctxs keep the x32 narrowing
    from ..util import x64_creation_scope

    with x64_creation_scope(kwargs.get("dtype", dtype), ctx):
        data = jfn(*args, **kwargs)
        if dtype is not None:
            from ..ndarray.ndarray import _dtype_np

            data = data.astype(_dtype_np(dtype))
        data = _jax.device_put(data, ctx.jax_device)
    return _wrap_arr(data, ctx, ndarray)


def zeros(shape, dtype=None, order="C", ctx=None, device=None):
    return _create(_jnp.zeros, (shape,), {"dtype": dtype or default_dtype()},
                   ctx=device or ctx)


def ones(shape, dtype=None, order="C", ctx=None, device=None):
    return _create(_jnp.ones, (shape,), {"dtype": dtype or default_dtype()},
                   ctx=device or ctx)


def full(shape, fill_value, dtype=None, order="C", ctx=None, device=None):
    if dtype is None and isinstance(fill_value, float):
        dtype = default_dtype()  # ints/bools follow fill_value like numpy
    return _create(_jnp.full, (shape, fill_value), {"dtype": dtype},
                   ctx=device or ctx)


def empty(shape, dtype=None, order="C", ctx=None, device=None):
    return zeros(shape, dtype=dtype, ctx=device or ctx)


def zeros_like(a, dtype=None, order="C", ctx=None, device=None):
    return apply_np(_jnp.zeros_like, "zeros_like", (a,), {"dtype": dtype})


def ones_like(a, dtype=None, order="C", ctx=None, device=None):
    return apply_np(_jnp.ones_like, "ones_like", (a,), {"dtype": dtype})


def full_like(a, fill_value, dtype=None, order="C", ctx=None, device=None):
    return apply_np(_jnp.full_like, "full_like", (a, fill_value),
                    {"dtype": dtype})


def empty_like(a, dtype=None, order="C", ctx=None, device=None):
    return zeros_like(a, dtype=dtype)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    return _create(_jnp.arange, (start, stop, step), {"dtype": dtype},
                   ctx=device or ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    if retstep:
        from ..util import x64_creation_scope

        dt = dtype or default_dtype()
        ctx = device or ctx or _current_context()
        with x64_creation_scope(dt, ctx):
            data, step = _jnp.linspace(start, stop, num, endpoint=endpoint,
                                       retstep=True, dtype=dt, axis=axis)
            data = _jax.device_put(data, ctx.jax_device)
        return _wrap_arr(data, ctx, ndarray), float(step)
    return _create(_jnp.linspace, (start, stop, num),
                   {"endpoint": endpoint, "dtype": dtype or default_dtype(),
                    "axis": axis},
                   ctx=device or ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    return _create(_jnp.logspace, (start, stop, num),
                   {"endpoint": endpoint, "base": base,
                    "dtype": dtype or default_dtype(),
                    "axis": axis}, ctx=device or ctx)


def eye(N, M=None, k=0, dtype=None, ctx=None, device=None):
    return _create(_jnp.eye, (N, M, k), {"dtype": dtype or default_dtype()},
                   ctx=device or ctx)


def identity(n, dtype=None, ctx=None, device=None):
    return eye(n, dtype=dtype, ctx=device or ctx)


def copy(a):
    return asarray(a).copy()


def may_share_memory(a, b, max_work=None):
    return False  # functional arrays never alias from the user's view


def shares_memory(a, b, max_work=None):
    return False


def insert(arr, obj, values, axis=None):
    return apply_np(_jnp.insert, "insert", (arr, obj, values),
                    {"axis": axis})


def delete(arr, obj, axis=None):
    return apply_np(_jnp.delete, "delete", (arr, obj), {"axis": axis})


# --- submodules ------------------------------------------------------------
from . import linalg  # noqa: E402
from . import random  # noqa: E402

_sys.modules[__name__ + ".linalg"] = linalg
_sys.modules[__name__ + ".random"] = random


# --- host-numpy fallback for the long tail ---------------------------------
def _fallback(name):
    """Reference ``numpy_op_fallback.py``: run on host numpy, wrap result.
    Synchronizes (host transfer) — fine for the rare tail ops."""
    ofn = getattr(_onp, name)

    def fn(*args, **kwargs):
        def unwrap(o):
            if isinstance(o, _NDArray):
                return o.asnumpy()
            if isinstance(o, (tuple, list)):
                return type(o)(unwrap(x) for x in o)
            return o

        res = ofn(*unwrap(list(args)), **{k: unwrap(v)
                                          for k, v in kwargs.items()})

        def wrap(o):
            if isinstance(o, _onp.ndarray):
                # dtype=None: host numpy computes in f64, the result must
                # follow the MXNet default-dtype rule (narrow to f32
                # unless the np_default_dtype scope is active) — the same
                # contract the reference fallback meets
                return array(o, dtype=None if o.dtype == _onp.float64
                             else o.dtype)
            if isinstance(o, (tuple, list)):
                return type(o)(wrap(x) for x in o)
            return o

        return wrap(res)

    fn.__name__ = name
    return fn


def __getattr__(name):
    if not name.startswith("_") and hasattr(_onp, name):
        attr = getattr(_onp, name)
        if callable(attr) and not isinstance(attr, type):
            fn = _fallback(name)
            setattr(_this, name, fn)
            return fn
        return attr
    raise AttributeError(f"module 'mxnet_tpu.numpy' has no attribute {name!r}")
