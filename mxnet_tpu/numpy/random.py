"""``mx.np.random`` — NumPy-style sampling on device.

Reference analog: ``src/operator/numpy/random/`` (`_npi_uniform` etc. over
curand).  TPU-native: counter-based threefry keys from the global chain
(:mod:`mxnet_tpu.random`) feeding ``jax.random`` samplers — reproducible and
trace-safe (inside a hybridized graph the key is an explicit input).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import random as _global_rng
from ..context import current_context
from ..ndarray.ndarray import NDArray, _wrap
from .multiarray import default_dtype, ndarray

__all__ = [
    "seed", "uniform", "normal", "randn", "rand", "randint", "choice",
    "shuffle", "permutation", "beta", "gamma", "exponential", "chisquare",
    "multinomial", "multivariate_normal", "logistic", "gumbel", "laplace",
    "pareto", "power", "rayleigh", "weibull", "lognormal", "binomial",
    "negative_binomial", "poisson", "f", "standard_normal", "standard_t",
    "standard_cauchy", "standard_exponential", "standard_gamma",
]


def seed(s):
    _global_rng.seed(s)


def _dev(ctx=None, device=None):
    return device or ctx or current_context()


def _maybe_x64(dtype, ctx):
    """Honest float64 sampling on CPU (single policy source:
    util.x64_creation_scope); accelerator ctxs keep the x32 narrowing."""
    from ..util import x64_creation_scope

    return x64_creation_scope(dtype, ctx)


def _wrap_dev(data, ctx):
    return _wrap(jax.device_put(data, ctx.jax_device), ctx, ndarray)


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _bshape(size, *params):
    if size is not None:
        return (size,) if isinstance(size, int) else tuple(size)
    shp = ()
    for p in params:
        p = _unwrap(p)
        if hasattr(p, "shape"):
            shp = onp.broadcast_shapes(shp, tuple(p.shape))
    return shp


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, device=None,
            out=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, low, high)
    dt = dtype or default_dtype()
    with _maybe_x64(dt, ctx):
        data = jax.random.uniform(_global_rng.next_key(), shp, dt,
                                  minval=_unwrap(low), maxval=_unwrap(high))
    return _wrap_dev(data, ctx)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None,
           out=None):
    if isinstance(scale, (int, float, onp.floating, onp.integer)) \
            and float(scale) < 0:
        # reference sample_op validates sigma >= 0 (MXNetError at sync)
        from ..error import MXNetError

        raise MXNetError(f"normal: scale must be non-negative, got {scale}")
    ctx = _dev(ctx, device)
    shp = _bshape(size, loc, scale)
    dt = dtype or default_dtype()
    with _maybe_x64(dt, ctx):
        data = jax.random.normal(_global_rng.next_key(), shp, dt)
        data = data * _unwrap(scale) + _unwrap(loc)
    return _wrap_dev(data, ctx)


def standard_normal(size=None, dtype=None, ctx=None, device=None):
    return normal(0.0, 1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def randn(*shape, ctx=None, device=None):
    return normal(size=shape or None, ctx=ctx, device=device)


def rand(*shape, ctx=None, device=None):
    return uniform(size=shape or None, ctx=ctx, device=device)


def randint(low, high=None, size=None, dtype=None, ctx=None, device=None,
            out=None):
    ctx = _dev(ctx, device)
    if high is None:
        low, high = 0, low
    shp = _bshape(size)
    if dtype is None:
        dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    data = jax.random.randint(_global_rng.next_key(), shp, low, high,
                              dtype=dtype)
    return _wrap_dev(data, ctx)


def choice(a, size=None, replace=True, p=None, ctx=None, device=None,
           out=None):
    ctx = _dev(ctx, device)
    a = _unwrap(a)
    if isinstance(a, int):
        a = jnp.arange(a)
    shp = _bshape(size)
    data = jax.random.choice(_global_rng.next_key(), a, shape=shp,
                             replace=replace, p=_unwrap(p) if p is not None else None)
    return _wrap_dev(data, ctx)


def permutation(x, ctx=None, device=None):
    ctx = _dev(ctx, device)
    data = jax.random.permutation(_global_rng.next_key(), _unwrap(x))
    return _wrap_dev(data, ctx)


def shuffle(x):
    """In-place shuffle along the first axis (reference _npi_shuffle)."""
    perm = jax.random.permutation(_global_rng.next_key(), x.shape[0])
    x._set_data(x._data[perm])


def beta(a, b, size=None, dtype=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, a, b)
    data = jax.random.beta(_global_rng.next_key(), _unwrap(a), _unwrap(b),
                           shape=shp or None, dtype=dtype or default_dtype())
    return _wrap_dev(data, ctx)


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None, device=None,
          out=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, shape, scale)
    dt = dtype or default_dtype()
    with _maybe_x64(dt, ctx):
        data = jax.random.gamma(_global_rng.next_key(), _unwrap(shape),
                                shape=shp or None,
                                dtype=dt) * _unwrap(scale)
    return _wrap_dev(data, ctx)


def standard_gamma(shape, size=None, dtype=None, ctx=None, device=None):
    return gamma(shape, 1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def exponential(scale=1.0, size=None, dtype=None, ctx=None, device=None,
                out=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, scale)
    data = jax.random.exponential(
        _global_rng.next_key(), shp, dtype or default_dtype()) * _unwrap(scale)
    return _wrap_dev(data, ctx)


def standard_exponential(size=None, dtype=None, ctx=None, device=None):
    return exponential(1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def chisquare(df, size=None, dtype=None, ctx=None, device=None):
    return gamma(jnp.asarray(_unwrap(df)) / 2.0, 2.0, size=size, dtype=dtype,
                 ctx=ctx, device=device)


def multinomial(n, pvals, size=None):
    ctx = current_context()
    pvals = jnp.asarray(_unwrap(pvals))
    shp = _bshape(size)
    cnt = jax.random.multinomial(_global_rng.next_key(), n, pvals,
                                 shape=(shp + pvals.shape) if shp else None)
    return _wrap_dev(cnt.astype(jnp.int64 if jax.config.jax_enable_x64
                                else jnp.int32), ctx)


def multivariate_normal(mean, cov, size=None, check_valid=None, tol=None):
    ctx = current_context()
    mean, cov = jnp.asarray(_unwrap(mean)), jnp.asarray(_unwrap(cov))
    shp = _bshape(size)
    data = jax.random.multivariate_normal(_global_rng.next_key(), mean, cov,
                                          shape=shp or None)
    return _wrap_dev(data, ctx)


def logistic(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, loc, scale)
    data = jax.random.logistic(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev(data * _unwrap(scale) + _unwrap(loc), ctx)


def gumbel(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, loc, scale)
    data = jax.random.gumbel(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev(data * _unwrap(scale) + _unwrap(loc), ctx)


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None,
            out=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, loc, scale)
    data = jax.random.laplace(_global_rng.next_key(), shp,
                              dtype or default_dtype())
    return _wrap_dev(data * _unwrap(scale) + _unwrap(loc), ctx)


def pareto(a, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, a)
    data = jax.random.pareto(_global_rng.next_key(), _unwrap(a),
                             shape=shp or None, dtype=default_dtype())
    return _wrap_dev(data - 1.0, ctx)  # numpy's pareto is lomax


def power(a, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, a)
    u = jax.random.uniform(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev(u ** (1.0 / jnp.asarray(_unwrap(a))), ctx)


def rayleigh(scale=1.0, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, scale)
    u = jax.random.uniform(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev(jnp.sqrt(-2.0 * jnp.log1p(-u)) * _unwrap(scale), ctx)


def weibull(a, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, a)
    u = jax.random.uniform(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev((-jnp.log1p(-u)) ** (1.0 / jnp.asarray(_unwrap(a))), ctx)


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None, device=None):
    n = normal(mean, sigma, size=size, ctx=ctx, device=device)
    return _wrap_dev(jnp.exp(n._data), n._ctx)


def binomial(n, p, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, n, p)
    data = jax.random.binomial(_global_rng.next_key(),
                               jnp.asarray(_unwrap(n), jnp.float32),
                               jnp.asarray(_unwrap(p), jnp.float32),
                               shape=shp or None)
    return _wrap_dev(data.astype(jnp.int32), ctx)


def negative_binomial(n, p, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, n, p)
    g = jax.random.gamma(_global_rng.next_key(),
                         jnp.broadcast_to(jnp.asarray(_unwrap(n), jnp.float32),
                                          shp or ()))
    p_ = jnp.asarray(_unwrap(p), jnp.float32)
    lam = g * (1.0 - p_) / p_
    data = jax.random.poisson(_global_rng.next_key(), lam, shape=shp or None)
    return _wrap_dev(data, ctx)


def poisson(lam=1.0, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, lam)
    data = jax.random.poisson(_global_rng.next_key(),
                              jnp.asarray(_unwrap(lam), jnp.float32),
                              shape=shp or None)
    return _wrap_dev(data, ctx)


def f(dfnum, dfden, size=None, ctx=None, device=None):
    num = chisquare(dfnum, size=size, ctx=ctx, device=device)
    den = chisquare(dfden, size=size, ctx=ctx, device=device)
    dfnum = jnp.asarray(_unwrap(dfnum), jnp.float32)
    dfden = jnp.asarray(_unwrap(dfden), jnp.float32)
    return _wrap_dev((num._data / dfnum) / (den._data / dfden), num._ctx)


def standard_t(df, size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size, df)
    data = jax.random.t(_global_rng.next_key(),
                        jnp.asarray(_unwrap(df), jnp.float32),
                        shape=shp or None)
    return _wrap_dev(data, ctx)


def standard_cauchy(size=None, ctx=None, device=None):
    ctx = _dev(ctx, device)
    shp = _bshape(size)
    data = jax.random.cauchy(_global_rng.next_key(), shp, default_dtype())
    return _wrap_dev(data, ctx)
