"""Compiled whole-train-step: the CachedOp analog for TRAINING.

The reference funnels every execution mode through ``CachedOp``
(``src/imperative/cached_op.cc``): a shape-keyed graph cache whose forward
AND backward run as one engine-scheduled graph each.  Our eager training
path, by contrast, still ran as a per-op vjp tape — forward dispatching
op-by-op, ``autograd.backward`` pushing one XLA program per ``TapeNode``,
and only the optimizer update fused (PR 1).  On chip every eager dispatch
pays a host round-trip (docs/PERF.md: BatchNorm 82 ms plain vs 0.3 ms
compiled), and the remaining ResNet reduce/copy texture (~37% of device
time) only fuses away when XLA sees forward and backward in ONE program.

:class:`TrainStep` (``Trainer.compile_step(net, loss_fn)``) closes that
gap: loss-fn forward (via the same staging machinery that backs
``HybridBlock.hybridize()`` — ``gluon.block._stage_fn``), the ``jax.vjp``
backward, the kvstore ``device``-path gradient reduction (an identity
reduce for the supported single-replica topology — multi-worker falls
back), the PR-1 functional ``Optimizer.fused_update`` rule
(``optimizer.fused.group_step_fn``, same numerics as the eager fused
path), and the AMP loss-scaling / all-finite gate all trace into ONE
``jax.jit`` program with DONATED parameter/optimizer-state buffers.

Programs are cached per ``TrainStep`` keyed by (input structure +
shapes/dtypes, train-mode, optimizer hyper-param signature, parameter/
state shapes+dtypes, AMP generation) — exactly CachedOp's shape-keyed
graph cache.  Per-step values (lr, wd, update counts, rescale_grad, the
loss scale) ride in as traced arguments, so an LR-scheduler tick or a
changed batch size never re-traces.

Result: dispatches/step drop from O(#tape nodes + #groups) to **1**
(+1 host scalar read for the AMP all-finite flag).  Anything the program
cannot express — a forward that cannot stage (host reads, data-dependent
shapes), ``grad_req='add'``, multi-replica parameters, dist/ps-lite
kvstores, server-side (``update_on_kvstore``) updates, optimizers without
a ``fused_update`` rule — falls back transparently to the eager tape;
``MXNET_COMPILED_STEP=0`` forces the tape everywhere.

**Pod-scale SPMD** (``kvstore='tpu'``): with an ICI-collective store the
step traces under a named ``jax.sharding.Mesh`` (``parallel.spmd``,
knob ``MXNET_SPMD_MESH``): the batch shards over the ``'dp'`` axis and
the gradient reduce this program already contains becomes an ICI-native
all-reduce scheduled by the XLA SPMD partitioner — overlappable with
backward, still ONE dispatch per step, still donated buffers.  Existing
Trainer code gets it by passing ``kvstore='tpu'``; the mesh (axes +
exact device set) is part of the program-cache key, inputs already
staged with the batch sharding (``engine.DevicePrefetcher``) pass
through without a copy, and steady state performs zero host-side
cross-device copies (``parallel.spmd.reshard_count``, pinned by the
dispatch-budget gate).  Host-driven stores (``dist_*``) still fall
back, naming this path.

**Beyond one chip's HBM** the same one-program contract extends to the
model-parallel axes and to gradient accumulation:

- an ``fsdp`` mesh axis (``MXNET_SPMD_MESH='dp=4,fsdp=2'``) shards
  parameters AND optimizer state at warmup (``spmd.param_spec``:
  largest evenly-divisible dim, indivisible leaves replicate loudly via
  ``sharding.legalize_refusal``); the per-leaf scatter/gather around
  the update is the XLA partitioner's schedule inside the one donated
  program — per-device param bytes drop ~1/N (gauges
  ``spmd.param_bytes_per_device`` / ``spmd.opt_bytes_per_device``);
- a ``tp`` axis honors model-code ``sharding.constraint`` annotations:
  the step traces AND dispatches inside the mesh context, so a
  constraint in a hybridizable forward resolves axis names without the
  mesh threaded through — composing with FSDP on the same mesh;
- ``Trainer.compile_step(..., accum_steps=N)`` splits the step into a
  grad-accumulation program (dispatched per microbatch, donated
  accumulator buffers sharded like their parameters) and ONE fused
  update program per window — exactly N+1 dispatches per window, the
  deferred AMP gate spanning the window (scale held fixed across it,
  overflow detected on the summed grads), lr/update-count semantics
  identical to one big-batch step.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import autograd
from . import config as _config
from . import engine as _engine
from . import faults as _faults
from . import program_store as _pstore
from . import random as _random
from . import telemetry as _telemetry
from .context import current_context

__all__ = ["TrainStep", "enabled", "trace_count", "dispatch_count",
           "cache_stats", "deferred_read_count", "reset_counters"]

# observability: this module's programs live in the ProgramStore
# 'train_step' namespace — traces bump when a whole-step program body is
# (re)traced, dispatches per compiled launch, hits/misses/evictions
# track the shape-keyed program cache.  The module-level functions below
# are views over that one shared surface (tools/check_dispatch_budget.py
# and benchmark/eager_latency.py read them; the bar: 1 dispatch/step,
# 0 retraces after warm-up).
_NS = _pstore.namespace("train_step")
_DEFERRED_READ = _telemetry.counter(
    "cached_step.deferred_read",
    "host reads of a LAGGED all-finite flag (the deferred AMP gate, "
    "MXNET_AMP_LAG): each reads step N-1's flag while step N is in "
    "flight, so it never blocks the current program")


def trace_count() -> int:
    return _NS.traces


def dispatch_count() -> int:
    return _NS.dispatches


def cache_stats() -> Dict[str, int]:
    return {"hits": _NS.hits, "misses": _NS.misses,
            "evictions": _NS.evictions}


def deferred_read_count() -> int:
    """Host reads of a LAGGED all-finite flag (the deferred AMP gate,
    MXNET_AMP_LAG): each is a read of step N-1's flag performed while
    step N is already in flight, so it never blocks on the current
    program.  (View over the ``cached_step.deferred_read`` registry
    counter.)"""
    return int(_DEFERRED_READ.value)


def reset_counters() -> None:
    _NS.reset()
    _DEFERRED_READ.reset()


def enabled() -> bool:
    """Compiled-step knob on (MXNET_COMPILED_STEP, default 1)."""
    return bool(_config.get("MXNET_COMPILED_STEP"))


class TrainStep:
    """One training step — forward, backward, reduce, update — as one
    compiled, donated XLA program (``Trainer.compile_step``).

    ``loss_fn(net, *args)`` must return NDArray loss value(s); calling the
    step runs the whole update and returns the (unscaled) loss.  The
    backward seeds ones over every loss leaf, exactly like
    ``autograd.backward`` on the eager tape, so ``step(x, y)`` is the
    compiled equivalent of::

        with autograd.record():
            loss = loss_fn(net, x, y)
        loss.backward()
        trainer.step(batch_size)

    Parameter ``.grad()`` buffers are NOT materialized on the compiled
    path (gradients live only inside the program); the eager fallback
    writes them as usual.
    """

    def __init__(self, net, loss_fn: Callable, trainer, bucket: bool = False,
                 accum_steps: int = 1):
        self._net = net
        self._loss_fn = loss_fn
        self._trainer = trainer
        # gradient accumulation (compile_step(accum_steps=N)): N
        # microbatch grad dispatches feed donated accumulator buffers,
        # then ONE fused update applies the window — N+1 dispatches,
        # one optimizer update-count bump, per window
        if int(accum_steps) < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self._accum_steps = int(accum_steps)
        self._accum_bufs: Optional[list] = None
        self._accum_key = None
        self._accum_i = 0
        # this step's keyspace in the ProgramStore 'train_step'
        # namespace: shared eviction (cap MXNET_COMPILED_STEP_CACHE /
        # MXNET_PROGRAM_CACHE_CAPS) + shared metrics, per-instance keys
        self._programs = _pstore.scope("train_step")
        # sticky: set on a staging/trace failure — the forward cannot
        # stage, so every later call takes the eager tape directly
        self.fallback_reason: Optional[str] = None
        # why the LAST call fell back (None when it ran compiled)
        self.last_fallback_reason: Optional[str] = None
        # shape bucketing (serving.BucketPolicy, MXNET_SHAPE_BUCKETS),
        # opt-in: variable-length batches pad up to the bucket grid so
        # they stop blowing the shape-keyed program cache.  The loss must
        # be PAD-SAFE (masked so zero rows contribute nothing — e.g. the
        # DataLoader last_batch='pad' valid count turned into a mask);
        # the first use of each bucket verifies the padded loss value
        # bit-exact vs the unpadded one and REFUSES bucketing on mismatch
        # (sticky, reason in bucket_refused) — numerics never change
        # silently.
        # graftlint: disable=host-sync -- host python flag, not a device read
        self._bucket = bool(bucket)
        self.bucket_refused: Optional[str] = None
        self._bucket_verified: set = set()
        self.padded_steps = 0
        # SPMD mesh (kvstore='tpu', MXNET_SPMD_MESH): resolved once the
        # kvstore exists (first __call__); None = single-chip path
        self._mesh = None
        self._mesh_resolved = False
        # deferred AMP gate (MXNET_AMP_LAG): the previous step's device
        # all-finite flag, not yet read on host.  The NEXT dispatch
        # carries both scale candidates and selects on this flag
        # on-device; the host read then happens while that dispatch is
        # in flight.  engine.waitall() drains it via drain().
        self._pending_ok = None
        # training-integrity sentinel (mxnet_tpu/sentinel.py): when
        # attached, sentinel-cadence dispatches flip the traced
        # want_digest flag so the program's lax.cond emits the state
        # fingerprint — same program, 0 extra dispatches/retraces
        self._sentinel = None
        _engine.register_drainable(self)

    def attach_sentinel(self, sentinel):
        """Attach a :class:`mxnet_tpu.sentinel.Sentinel`: it decides the
        digest cadence (``want_digest`` per compiled dispatch) and
        receives the emitted device fingerprint via ``offer``."""
        self._sentinel = sentinel
        return sentinel

    # -- public ----------------------------------------------------------
    @property
    def last_step_compiled(self) -> bool:
        return self.last_fallback_reason is None

    @property
    def mesh(self):
        """The SPMD mesh this step traces under (``None`` single-chip)."""
        return self._mesh

    @property
    def batch_sharding(self):
        """The ``NamedSharding`` input batches should be staged with —
        hand it to ``engine.prefetch(..., sharding=)`` / ``DataLoader(...,
        sharding=)`` so the prefetch thread's ``device_put`` already
        lands shards on the mesh and the step pays no re-placement.
        ``None`` when the step is single-chip."""
        if not self._mesh_resolved and not self._trainer._kv_initialized:
            self._trainer._init_kvstore()    # the mesh follows the store
        if self._resolve_mesh() is None:
            return None
        from .parallel import spmd as _spmd

        return _spmd.batch_sharding(self._mesh)

    def _params_on_mesh(self) -> bool:
        """True once the compiled mesh path actually replicated the
        parameters across >1 device (a fallback BEFORE placement keeps
        plain single-device eager semantics)."""
        for p in self._trainer._params:
            if p.grad_req == "null" or p._data is None:
                continue
            sh = getattr(p.data()._data, "sharding", None)
            return sh is not None and len(sh.device_set) > 1
        return False

    def _resolve_mesh(self):
        if not self._mesh_resolved:
            from .parallel import spmd as _spmd

            kv = self._trainer._kvstore
            self._mesh = _spmd.mesh_for_store(
                getattr(kv, "type", None)) if kv is not None else None
            self._mesh_resolved = True
        return self._mesh

    def drain(self) -> None:
        """Read the pending deferred AMP flag (if any) and apply the
        loss-scale policy, catching the host scaler state up to the
        device.  Called by ``engine.waitall()``, before any eager-tape
        fallback, and whenever the lag window closes (MXNET_AMP_LAG=0 /
        NaiveEngine) — after drain() the scaler state equals the
        synchronous gate's bit-exactly."""
        prev, self._pending_ok = self._pending_ok, None
        if prev is None:
            return
        from .ndarray import ndarray as _ndmod

        _ndmod.count_host_sync()
        _DEFERRED_READ.inc()
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        if scaler is not None:
            # graftlint: disable=host-sync -- the deliberate deferred AMP
            # gate read at drain time, counted via count_host_sync above
            overflow = not bool(prev)
            if overflow:
                _telemetry.event("amp_overflow", "cached_step",
                                 where="drain")
            scaler.update_scale(overflow)

    def __call__(self, *args, batch_size: Optional[int] = None):
        # train-step injection site (fail-fast like trainer.step: a step
        # is not idempotent; recovery is restore-and-replay, not retry)
        _faults.inject("cached_step.step")
        step_idx = _telemetry.next_step()
        with _telemetry.span("train_step.step", cat="train_step") as sp:
            return self._call_inner(args, batch_size, step_idx, sp)

    def _call_inner(self, args, batch_size, step_idx, sp):
        tr = self._trainer
        if batch_size is None:
            batch_size = int(args[0].shape[0]) \
                if args and getattr(args[0], "shape", ()) else 1
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        reason = self._eligibility()
        if reason is not None:
            if reason != self.last_fallback_reason:
                _telemetry.event("fallback", "cached_step", reason=reason)
            self.last_fallback_reason = reason
            sp.annotate(path="eager", step=step_idx)
            return self._eager_step(args, batch_size)
        opt = tr._optimizer
        # host-side update-count bump BEFORE reading lrs (the eager order:
        # Optimizer.update -> _update_count -> _get_lrs); snapshotted so a
        # build failure can roll back before the eager fallback re-bumps
        indices = [tr._param2idx[id(p)] for p in tr._params
                   if p.grad_req != "null"]
        count_snap = (dict(opt._index_update_count), opt.num_update)
        pargs = self._maybe_pad(args)
        # with accumulation only the window-FINAL microbatch applies an
        # update, so only it bumps the counts — lr schedules and
        # momentum counts see one step per window, not per microbatch
        window_final = (self._accum_steps == 1
                        or self._accum_i == self._accum_steps - 1)
        if window_final:
            opt._update_count(list(indices))
        try:
            out = self._compiled_step(pargs, batch_size)
        except Exception as e:  # staging/trace failure -> sticky fallback
            opt._index_update_count.clear()
            opt._index_update_count.update(count_snap[0])
            opt.num_update = count_snap[1]
            self.fallback_reason = f"{type(e).__name__}: {e}"
            self.last_fallback_reason = self.fallback_reason
            _telemetry.event("fallback", "cached_step",
                             reason=self.fallback_reason, sticky=True)
            sp.annotate(path="eager", step=step_idx)
            return self._eager_step(args, batch_size)
        self.last_fallback_reason = None
        sp.annotate(path="compiled", step=step_idx)
        return out

    # -- shape bucketing --------------------------------------------------
    def _maybe_pad(self, args):
        """Pad the batch axis of every input leaf up to its bucket
        (``serving.BucketPolicy``) so variable-length batches share one
        program per bucket.  Applies only with ``compile_step(...,
        bucket=True)``; verified once per bucketed signature (the padded
        loss must be bit-exact vs the unpadded loss — a pad-safe/masked
        loss), refused sticky otherwise.  Returns the (possibly padded)
        args; the eager fallback always sees the ORIGINAL args."""
        if not self._bucket or self.bucket_refused is not None:
            return args
        try:
            from . import serving as _serving
            from .gluon import block as _gb
            from .ndarray.ndarray import _wrap

            policy = _serving.BucketPolicy()
            if not policy.enabled:
                return args
            leaves, struct = _gb._flatten_args(args)
            if not leaves or any(len(l.shape) < 1 for l in leaves):
                return args
            n = int(leaves[0].shape[0])
            b = policy.bucket(n)
            if b is None or b == n:
                return args
            key = (_gb._struct_key(struct), b,
                   tuple((tuple(l.shape), str(l._data.dtype))
                         for l in leaves))
            pad = [_wrap(_serving.pad_axis0(l._data, b), l.ctx, type(l))
                   if int(l.shape[0]) == n else l for l in leaves]
            pargs = _gb._unflatten_args(struct, pad)
            if _config.get("MXNET_SERVE_VERIFY") and \
                    key not in self._bucket_verified:
                reason = self._verify_pad(args, pargs)
                if reason is not None:
                    self.bucket_refused = reason
                    return args
                self._bucket_verified.add(key)
            self.padded_steps += 1
            return tuple(pargs)
        except Exception as e:
            self.bucket_refused = f"{type(e).__name__}: {e}"
            return args

    def _verify_pad(self, args, pargs) -> Optional[str]:
        """One loss-only eager evaluation of both the true and the padded
        batch (recording off, train mode, parameter buffers snapshotted
        and restored so a mutating forward — BN batch stats — cannot
        leak).  Equal loss values prove the loss masks pad rows; any
        difference refuses bucketing BEFORE a single padded gradient is
        applied."""
        import numpy as onp

        from .gluon import block as _gb

        reps = [d for p in self._net.collect_params().values()
                if p._data is not None for d in p._data]
        snap = [(d, d._data, d._version) for d in reps]
        try:
            with autograd.pause(train_mode=True):
                lt = self._loss_fn(self._net, *args)
                lp = self._loss_fn(self._net, *pargs)
        finally:
            for d, old, ver in snap:
                d._data = old
                d._version = ver
        lt_leaves, _ = _gb._flatten_output(lt)
        lp_leaves, _ = _gb._flatten_output(lp)
        if len(lt_leaves) != len(lp_leaves):
            return "padded loss structure differs from unpadded"
        for t, p in zip(lt_leaves, lp_leaves):
            # graftlint: disable=host-sync -- one-time pad-safety verify
            # per bucket signature, off the steady-state step path
            tn, pn = t.asnumpy(), p.asnumpy()
            if tn.shape != pn.shape or not onp.array_equal(tn, pn):
                return ("padded loss differs from unpadded — the loss is "
                        "not pad-safe (mask pad rows, e.g. with the "
                        "DataLoader last_batch='pad' valid count, or use "
                        "a sum-style masked reduction)")
        return None

    # -- eligibility / fallback ------------------------------------------
    def _eligibility(self) -> Optional[str]:
        from .optimizer import fused as _fused

        tr = self._trainer
        if not enabled():
            return "MXNET_COMPILED_STEP=0"
        if self.fallback_reason is not None:
            return self.fallback_reason
        if not _fused.supports(tr._optimizer):
            return (f"optimizer {type(tr._optimizer).__name__} has no "
                    "functional fused_update rule")
        if tr._update_on_kvstore:
            return "update_on_kvstore=True applies updates server-side"
        mesh = self._resolve_mesh()
        if tr._kvstore is not None and tr._kvstore.num_workers > 1 \
                and mesh is None:
            return (f"multi-worker '{tr._kvstore.type}' kvstore reduction "
                    "is host-driven (dist/ps-lite); the staged SPMD "
                    "all-reduce covers kvstore='tpu' (pod-scale SPMD "
                    "training, ISSUE 6)")
        for p in tr._params:
            if p.grad_req == "add":
                return f"parameter '{p.name}' has grad_req='add'"
        for p in self._net.collect_params().values():
            if p._data is None:
                return ("deferred parameter init pending (first call "
                        "runs eagerly, like hybridize)")
            if len(p._data) > 1:
                return "multi-replica (multi-ctx) parameters"
        return None

    def _eager_step(self, args, batch_size):
        """The eager tape path, AMP-equivalent to amp.scale_loss +
        backward + trainer.step."""
        if self._accum_steps > 1:
            # the eager tape applies one update PER call — silently
            # turning an N-microbatch window into N full steps would
            # change lr/count semantics, so accumulation refuses the
            # tape loudly instead of degrading wrong
            from .base import MXNetError

            raise MXNetError(
                f"accum_steps={self._accum_steps} requires the compiled "
                "step (one fused update per window); the eager tape "
                "cannot honor the window contract — fallback reason: "
                f"{self.last_fallback_reason}")
        # a pending deferred flag must land first: the eager step reads
        # scaler.loss_scale synchronously, so the host state has to be
        # caught up to the device before this step's scale is chosen
        self.drain()
        tr = self._trainer
        if self._mesh is not None and self._params_on_mesh():
            # a sticky fallback AFTER mesh placement: the parameters
            # already live replicated across the mesh, and eager ops
            # require colocated operands — stage the batch replicated too
            from .parallel import spmd as _spmd

            rep = _spmd.replicated(self._mesh)

            def _rep(a):
                if isinstance(a, (tuple, list)):
                    return type(a)(_rep(v) for v in a)
                if hasattr(a, "_data"):
                    from .ndarray import ndarray as _nd

                    return _nd._wrap(jax.device_put(a._data, rep),
                                     a.ctx, type(a))
                return a
            args = tuple(_rep(a) for a in args)
        scaler = getattr(tr, "_amp_loss_scaler", None)
        from .parallel import moe as _moe
        with autograd.record():
            with _moe.aux_scope() as auxes:
                loss = self._loss_fn(self._net, *args)
            heads = list(loss) if isinstance(loss, (list, tuple)) else [loss]
            if auxes:
                # MoE load-balance loss: same extra differentiated head
                # the compiled program folds, so eager == compiled
                aux_w = float(_config.get("MXNET_MOE_AUX_WEIGHT"))
                at = auxes[0]
                for a in auxes[1:]:
                    at = at + a
                heads = heads + [at * aux_w]
            if scaler is not None and scaler.loss_scale != 1.0:
                heads = [h * scaler.loss_scale for h in heads]
        autograd.backward(heads)
        gt = getattr(self._net, "compiled_grad_transform", None)
        if gt is not None:
            named = {}
            for n, p in self._net.collect_params().items():
                if p.grad_req != "null" and p._grad is not None:
                    named[n] = p.grad()._data
            for n, g in gt(dict(named)).items():
                if named.get(n) is not g:
                    self._net.collect_params()[n].grad()._set_data(g)
        if scaler is not None:
            base = getattr(tr, "_amp_original_scale", tr._scale)
            tr._amp_original_scale = base
            tr._scale = base / scaler.loss_scale
        tr.step(batch_size)
        return loss

    # -- the compiled step ------------------------------------------------
    def _prep(self):
        """State-side preparation shared by dispatch and
        :meth:`precompile`: parameter/optimizer-state layout, update
        groups, and (under a mesh) the one-time replicated placement.
        Depends only on trainer/net state, never on the input batch."""
        from types import SimpleNamespace

        from .optimizer import fused as _fused

        tr = self._trainer
        opt = tr._optimizer
        scaler = getattr(tr, "_amp_loss_scaler", None)
        updater = tr._updaters[0]

        params = OrderedDict(
            (n, p) for n, p in self._net.collect_params().items()
            if p._data is not None)
        names = list(params)
        # trainable set/order follows trainer._params — the order the
        # eager fused path groups and checks finiteness in
        trainable = [p for p in tr._params if p.grad_req != "null"]
        indices = [tr._param2idx[id(p)] for p in trainable]
        for p, idx in zip(trainable, indices):
            if idx not in updater.states:
                updater.states[idx] = opt.create_state_multi_precision(
                    idx, p.data())
                updater.states_synced[idx] = True
        states = [updater.states[idx] for idx in indices]
        mps = [_fused._is_mp_state(opt, p.data(), s)
               for p, s in zip(trainable, states)]
        groups: "OrderedDict" = OrderedDict()
        for i, p in enumerate(trainable):
            groups.setdefault((p.data()._data.dtype, mps[i]), []).append(i)
        group_layout = tuple((mp, tuple(m))
                             for (_dt, mp), m in groups.items())

        slot_of_name: Dict[str, int] = {}
        trainable_ids = {id(p): i for i, p in enumerate(trainable)}
        for n in names:
            i = trainable_ids.get(id(params[n]))
            if i is not None:
                slot_of_name[n] = i
        frozen_names = [n for n in names if n not in slot_of_name]

        mesh = self._mesh
        rep = None
        if mesh is not None:
            from .parallel import spmd as _spmd

            rep = _spmd.replicated(mesh)
            model_axes = _spmd.model_axes_active(mesh)
            name_of = {id(p): n for n, p in params.items()}

            def _sharding_of(shape, pname=None):
                # any model axis present (fsdp/pp/ep): per-leaf
                # name+shape-aware placement — pp packed stage buffers
                # and ep expert weights by NAME, then the ZeRO rule
                # (largest divisible dim, small/indivisible leaves
                # replicate — the latter loudly); otherwise the classic
                # replicated KVStore-broadcast layout
                if model_axes:
                    return _spmd.param_sharding(tuple(shape), mesh,
                                                name=pname)
                return rep

            def _place_nd(d, sh=None):
                new = _spmd.ensure_placed(
                    d._data, sh if sh is not None else rep)
                if new is not d._data:
                    d._set_data(new)

            def _place_state(s, wshape, wsh):
                # optimizer-state leaves SHAPED like their weight
                # (momentum, Adam moments, the fp32 master copy) shard
                # with it — that is the ZeRO part of FSDP; scalars and
                # odd-shaped leaves replicate
                if s is None:
                    return
                if hasattr(s, "_set_data"):
                    same = tuple(s.shape) == tuple(wshape)
                    _place_nd(s, wsh if same else rep)
                    return
                for x in s:
                    _place_state(x, wshape, wsh)

            # one-time placement (the KVStore init/broadcast analog):
            # steady state sees already-placed buffers — the step's
            # outputs carry the same shardings back into the
            # parameters, so reshard_count stays flat after warmup
            for p in trainable:
                _place_nd(p.data(), _sharding_of(p.data().shape,
                                                 name_of.get(id(p))))
            for n in frozen_names:
                _place_nd(params[n].data(),
                          _sharding_of(params[n].data().shape, n))
            for p, s in zip(trainable, states):
                _place_state(s, p.data().shape,
                             _sharding_of(p.data().shape,
                                          name_of.get(id(p))))

            # per-device memory accounting (gauges
            # spmd.param_bytes_per_device / spmd.opt_bytes_per_device):
            # computed from the placed leaves' ACTUAL shardings, so the
            # fsdp layout reads ~1/N of the replicated one
            _spmd.record_layout(
                [p.data()._data for p in trainable]
                + [params[n].data()._data for n in frozen_names],
                [l for s in states
                 for l in jax.tree_util.tree_leaves(_fused._unwrap(s))])

        return SimpleNamespace(
            opt=opt, scaler=scaler, updater=updater, params=params,
            names=names, trainable=trainable, indices=indices,
            states=states, group_layout=group_layout,
            slot_of_name=slot_of_name, frozen_names=frozen_names,
            mesh=mesh, rep=rep, has_ok=scaler is not None,
            donate=jax.default_backend() not in ("cpu",))

    def _signature(self, prep, in_struct_key, in_specs, ctx, flavor):
        """The program-cache key: input structure + shapes/dtypes ×
        train-mode × hyper-param signature × parameter/state layout ×
        mesh — ``in_specs`` is ``tuple((shape, dtype), ...)`` so real
        leaves and abstract precompile specs key identically."""
        from .ndarray import ndarray as _ndmod
        from .optimizer import fused as _fused

        mesh = prep.mesh
        if mesh is not None:
            from .parallel import spmd as _spmd
        return (
            in_struct_key,
            tuple(in_specs),
            True,                       # train-mode (part of the key by
            _ndmod._amp_generation,     # contract; TrainStep trains)
            ctx, flavor,
            type(prep.opt).__name__, prep.opt._fused_signature(),
            tuple((tuple(p.data().shape), p.data()._data.dtype)
                  for p in prep.trainable),
            tuple(_fused._struct(s) for s in prep.states),
            tuple((n, tuple(prep.params[n].data().shape),
                   prep.params[n].data()._data.dtype)
                  for n in prep.frozen_names),
            prep.group_layout, prep.has_ok, prep.donate,
            # the SPMD mesh (axes + exact device set): a topology change
            # must never reuse a program compiled for another
            None if mesh is None else _spmd.mesh_key(mesh),
        )

    def _mesh_ctx(self, mesh):
        """The mesh context the step traces AND dispatches under: inside
        it ``sharding.constraint`` calls in model code resolve the
        ``'tp'``/``'fsdp'`` axis names without the mesh threaded through
        the call stack (single-chip: a no-op context)."""
        if mesh is None:
            import contextlib

            return contextlib.nullcontext()
        from .parallel.mesh import mesh_scope

        return mesh_scope(mesh)

    def _ensure_program(self, sig, prep, in_struct, ctx, flavor,
                        lower_args, kind="full"):
        """One code path for warm-up, steady state, and elastic restore:
        resolve ``sig`` through the ProgramStore — a miss traces AND
        AOT-compiles (persisting to MXNET_PROGRAM_CACHE_DIR when set)
        before any dispatch.  ``kind`` selects the program body: the
        whole fused step (``'full'``), the accumulation-window grad
        program (``'grad'``), or the window-closing update program
        (``'update'``).  Tracing happens inside the mesh context so
        model-code sharding constraints resolve."""
        rec = self._programs.lookup(sig)
        if rec is None:
            with self._mesh_ctx(prep.mesh):
                if kind == "full":
                    jitted, out_struct, mutated_names = \
                        self._build_program(
                            prep.params, prep.names, in_struct, ctx,
                            flavor, prep.slot_of_name, prep.frozen_names,
                            prep.group_layout, prep.has_ok, prep.donate)
                elif kind == "grad":
                    jitted, out_struct, mutated_names = \
                        self._build_grad_program(
                            prep.params, prep.names, in_struct, ctx,
                            flavor, prep.slot_of_name, prep.frozen_names,
                            prep.has_ok, prep.donate)
                else:
                    jitted = self._build_update_program(
                        prep.group_layout, prep.has_ok, prep.donate)
                    out_struct, mutated_names = None, ()
                rec = _pstore.build(
                    "train_step", jitted, lower_args,
                    meta=(out_struct, mutated_names),
                    label=type(self._net).__name__)
            self._programs.insert(sig, rec)
        return rec

    def precompile(self, *specs, batch_size=None):
        """Ahead-of-time compilation of the train step from abstract
        input shapes, BEFORE the first batch arrives (deploy-time /
        elastic-restore warm-up; `Trainer.precompile` wraps this).

        ``specs`` are the step's positional inputs, each either a real
        NDArray example or a ``(shape, dtype)`` pair.  The program is
        traced and XLA-compiled through the ProgramStore exactly as the
        first dispatch would — with ``MXNET_PROGRAM_CACHE_DIR`` set the
        executable also lands in the persistent cache, so a later
        process skips the compile entirely.  No data is touched, no
        step runs, no parameter/optimizer state changes (under a mesh,
        parameters take their one-time replicated placement, exactly as
        the first step would).  Raises when the step would fall back to
        the eager tape (a silent warm-up of nothing helps no one).
        Returns ``self`` so ``trainer.precompile(...)`` chains."""
        import numpy as onp

        from .base import MXNetError
        from .gluon import block as _gb
        from .ndarray import ndarray as _ndmod

        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        reason = self._eligibility()
        if reason is not None:
            raise MXNetError(
                f"precompile: the compiled step would fall back to the "
                f"eager tape ({reason})")
        nd_specs = [s for s in specs if hasattr(s, "_data")]
        if nd_specs and len(nd_specs) == len(specs):
            in_leaves, in_struct = _gb._flatten_args(tuple(specs))
            shapes = [tuple(l.shape) for l in in_leaves]
            dtypes = [l._data.dtype for l in in_leaves]
            ctx = in_leaves[0].ctx if in_leaves else current_context()
            flavor = _ndmod._flavor_of(in_leaves)
        else:
            shapes, dtypes = [], []
            for s in specs:
                shape, dtype = s
                shapes.append(tuple(int(d) for d in shape))
                dtypes.append(onp.dtype(dtype))
            # flat positional args: the same treedef _flatten_args
            # produces for step(x, y, ...)
            in_struct = [("_leaf_", i) for i in range(len(specs))]
            ctx = current_context()
            flavor = _ndmod._flavor_of([])
        if self._bucket and self.bucket_refused is None and shapes:
            # precompile the PADDED program the bucketed step dispatches
            from . import serving as _serving

            policy = _serving.BucketPolicy()
            if policy.enabled:
                n = shapes[0][0]
                b = policy.bucket(n)
                if b is not None and b != n:
                    shapes = [(b,) + s[1:] if s and s[0] == n else s
                              for s in shapes]

        prep = self._prep()
        sig = self._signature(
            prep, _gb._struct_key(in_struct),
            tuple((s, d) for s, d in zip(shapes, dtypes)), ctx, flavor)
        in_sds = [jax.ShapeDtypeStruct(s, d)
                  for s, d in zip(shapes, dtypes)]
        if self._accum_steps > 1:
            # the accumulation window runs TWO programs: warm both
            usig = self._update_sig(prep, ctx, flavor)
            self._ensure_accum_bufs(prep, usig)
            self._ensure_program(
                ("accum_grad", self._accum_steps) + sig, prep, in_struct,
                ctx, flavor, self._grad_lower_args(prep, in_sds),
                kind="grad")
            self._ensure_program(
                usig, prep, None, ctx, flavor,
                self._update_lower_args(prep), kind="update")
        else:
            self._ensure_program(sig, prep, in_struct, ctx, flavor,
                                 self._lower_args(prep, in_sds))
        return self

    def _lower_args(self, prep, in_specs):
        """Abstract lowering arguments matching the dispatch call
        signature: real parameter/state buffers (their avals ARE the
        program's), ShapeDtypeStructs for the batch (mesh-sharded like
        ``spmd.put_batch`` would shard the real batch), abstract
        scalars for the per-step traced values."""
        f32 = jax.ShapeDtypeStruct((), jnp.float32)
        mesh = prep.mesh
        if mesh is not None:
            from .parallel import spmd as _spmd

            # batch divisibility follows the 'dp' axis size ONLY — on a
            # multi-axis mesh (dp×fsdp/tp) the whole-mesh device count
            # is NOT the batch-sharding divisor (matching batch_spec_for,
            # so the precompiled program equals the dispatched one)
            n_dp = int(mesh.shape.get(_spmd.DATA_AXIS, 1))
            bsh = _spmd.batch_sharding(mesh)

            def _in_spec(s):
                sh = bsh if (s.shape and s.shape[0] % n_dp == 0) \
                    else prep.rep
                return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)

            in_specs = [_in_spec(s) for s in in_specs]
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_,
                                           sharding=prep.rep)
            want_dig = jax.ShapeDtypeStruct((), jnp.bool_,
                                            sharding=prep.rep)
        else:
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_)
            want_dig = jax.ShapeDtypeStruct((), jnp.bool_)
        g32 = [jax.ShapeDtypeStruct((len(m),), jnp.float32)
               for _mp, m in prep.group_layout]
        from .optimizer import fused as _fused

        w_args = [p.data()._data for p in prep.trainable]
        s_args = tuple(_fused._unwrap(s) for s in prep.states)
        frozen_args = [prep.params[n].data()._data
                       for n in prep.frozen_names]
        return (w_args, s_args, frozen_args, list(in_specs),
                jax.random.PRNGKey(0), list(g32), list(g32), list(g32),
                f32, f32, f32, f32, prev_ok, want_dig)

    def _compiled_step(self, args, batch_size):
        from .gluon import block as _gb
        from .ndarray import ndarray as _ndmod
        from .optimizer import fused as _fused

        if self._accum_steps > 1:
            return self._accum_compiled_step(args, batch_size)
        tr = self._trainer
        in_leaves, in_struct = _gb._flatten_args(args)
        ctx = in_leaves[0].ctx if in_leaves else current_context()
        flavor = _ndmod._flavor_of(in_leaves)

        prep = self._prep()
        opt, scaler = prep.opt, prep.scaler
        indices, group_layout = prep.indices, prep.group_layout
        trainable, states = prep.trainable, prep.states
        mesh, rep = prep.mesh, prep.rep
        sig = self._signature(
            prep, _gb._struct_key(in_struct),
            tuple((tuple(l.shape), l._data.dtype) for l in in_leaves),
            ctx, flavor)

        # per-step traced values: counts were bumped by __call__ already
        counts = [opt._index_update_count[i] for i in indices]
        lrs = opt._get_lrs(list(indices))
        wds = opt._get_wds(list(indices))
        # deferred AMP gate (MXNET_AMP_LAG): while a previous step's
        # all-finite flag is unread, this step dispatches speculatively
        # with BOTH scale candidates — the clean-branch scale and the
        # overflow-branch scale, each computed by the SAME host policy
        # the synchronous gate runs — and the program selects on the
        # device flag.  Numerics are bit-exact vs the synchronous gate
        # because the selected candidate IS the value sync would pass.
        lag = _engine.amp_lag() if scaler is not None else 0
        if not lag:
            self.drain()          # lag window closed: catch up first
        if scaler is not None and lag and self._pending_ok is not None:
            s_clean, s_over = scaler.branch_scales()
        elif scaler is not None:
            s_clean = s_over = scaler.loss_scale
        else:
            s_clean = s_over = 1.0
        scale_val = s_clean
        if scaler is not None:
            tr._amp_original_scale = getattr(
                tr, "_amp_original_scale", tr._scale)
        base = getattr(tr, "_amp_original_scale", tr._scale)
        rescale = base / (scale_val * batch_size)
        rescale_alt = base / (s_over * batch_size)
        if self._pending_ok is not None:
            prev_ok = self._pending_ok
        elif mesh is not None:
            # pin the seed flag to the mesh so the first deferred step
            # traces with the same (replicated) sharding later flags
            # carry — otherwise step 2 would pay a one-off retrace
            prev_ok = jax.device_put(jnp.asarray(True), rep)
        else:
            prev_ok = jnp.asarray(True)
        lrs_g = [jnp.asarray([lrs[i] for i in m], jnp.float32)
                 for _mp, m in group_layout]
        wds_g = [jnp.asarray([wds[i] for i in m], jnp.float32)
                 for _mp, m in group_layout]
        counts_g = [jnp.asarray([counts[i] for i in m], jnp.float32)
                    for _mp, m in group_layout]

        w_args = [p.data()._data for p in trainable]
        s_args = tuple(_fused._unwrap(s) for s in states)
        frozen_args = [prep.params[n].data()._data
                       for n in prep.frozen_names]
        if mesh is not None:
            from .parallel import spmd as _spmd

            # batch leaves shard over 'dp' (legalized: an indivisible
            # batch axis replicates, loudly).  Leaves the prefetcher
            # already staged with this sharding pass through untouched.
            in_args = [_spmd.put_batch(l._data, mesh) for l in in_leaves]
        else:
            in_args = [l._data for l in in_leaves]

        # sentinel cadence: the traced want_digest flag selects the
        # in-program lax.cond digest branch — value changes never
        # retrace, and under a mesh the flag pins replicated exactly
        # like the seed AMP flag above
        snt = self._sentinel
        want_digest = snt is not None and snt.want_digest()
        if mesh is not None:
            want_arg = jax.device_put(jnp.asarray(want_digest), rep)
        else:
            want_arg = jnp.asarray(want_digest)
        call_args = (
            w_args, s_args, frozen_args, in_args, _random.next_key(),
            lrs_g, wds_g, counts_g,
            jnp.asarray(rescale, jnp.float32),
            jnp.asarray(scale_val, jnp.float32),
            jnp.asarray(s_over, jnp.float32),
            jnp.asarray(rescale_alt, jnp.float32),
            prev_ok, want_arg)
        rec = self._ensure_program(sig, prep, in_struct, ctx, flavor,
                                   call_args)
        out_struct, mutated_names = rec.meta
        with self._mesh_ctx(mesh):
            out_raw, mut_vals, new_w, new_s, ok, dig = rec(*call_args)
        if want_digest:
            # hand the UNREAD device fingerprint to the sentinel; it
            # consumes the previous pending one (deferred a full
            # cadence — that program retired long ago, so the read
            # rides the PR-5 lag machinery, never a stall on this step)
            snt.offer(*dig)

        for p, nw in zip(trainable, new_w):
            p._data[0]._set_data(nw)
        for s, ns in zip(states, new_s):
            _fused._write(s, ns)
        # mutation (BN running stats) writes LAST: a forward mutating a
        # TRAINABLE param cannot be expressed in one program — its
        # mutation wins this step and the step goes sticky-eager
        for n, v in zip(mutated_names, mut_vals):
            prep.params[n]._data[0]._set_data(v)
        overlap = [n for n in mutated_names if n in prep.slot_of_name]
        if overlap:
            self.fallback_reason = (
                f"forward mutates trainable parameter(s) {overlap}")
        out_nd = [_ndmod._wrap(o, ctx, flavor) for o in out_raw]
        loss = _gb._rebuild_output(out_struct[0], out_nd)
        if scaler is not None:
            if lag:
                # deferred gate: hold THIS step's flag, read the
                # PREVIOUS one (already materialized — its program
                # finished while this step was being prepared, so the
                # read is lagged, never a stall on the current program)
                prev = self._pending_ok
                self._pending_ok = ok
                if prev is not None:
                    _ndmod.count_host_sync()
                    _DEFERRED_READ.inc()
                    # graftlint: disable=host-sync -- the ONE deferred AMP
                    # gate read per step (lagged: never blocks the current
                    # program), counted via count_host_sync
                    overflow = not bool(prev)
                    if overflow:
                        _telemetry.event("amp_overflow", "cached_step",
                                         where="deferred")
                    scaler.update_scale(overflow)
            else:
                # the ONE host read of the step: the device all-finite
                # flag drives the loss-scale policy synchronously
                _ndmod.count_host_sync()
                # graftlint: disable=host-sync -- the documented synchronous
                # AMP gate read (MXNET_AMP_LAG=0 / NaiveEngine), counted
                overflow = not bool(ok)
                if overflow:
                    _telemetry.event("amp_overflow", "cached_step",
                                     where="sync")
                scaler.update_scale(overflow)
        return loss

    # -- gradient accumulation (compile_step(accum_steps=N)) --------------
    def _update_sig(self, prep, ctx, flavor):
        """The window-closing update program's cache key: it never sees
        the batch, so input structure/shapes are deliberately absent —
        alternating microbatch shapes share ONE update program (and one
        set of accumulator buffers)."""
        from .ndarray import ndarray as _ndmod
        from .optimizer import fused as _fused

        mesh = prep.mesh
        if mesh is not None:
            from .parallel import spmd as _spmd
        return (
            "accum_update", self._accum_steps, ctx, flavor,
            _ndmod._amp_generation,
            type(prep.opt).__name__, prep.opt._fused_signature(),
            tuple((tuple(p.data().shape), p.data()._data.dtype)
                  for p in prep.trainable),
            tuple(_fused._struct(s) for s in prep.states),
            prep.group_layout, prep.has_ok, prep.donate,
            None if mesh is None else _spmd.mesh_key(mesh),
        )

    def _ensure_accum_bufs(self, prep, key) -> None:
        """Donation-safe gradient accumulators: one zeros buffer per
        trainable param, placed with the SAME sharding (fsdp-sharded
        grads accumulate shard-local, no gather).  Built once per
        (param-layout, mesh) signature; the update program returns
        freshly ZEROED buffers in the donated slots, so steady state
        never pays an eager zeros dispatch."""
        if self._accum_bufs is not None and self._accum_key == key:
            return
        bufs = []
        for p in prep.trainable:
            w = p.data()._data
            z = jnp.zeros(w.shape, w.dtype)
            if prep.mesh is not None:
                z = jax.device_put(z, w.sharding)
            bufs.append(z)
        self._accum_bufs = bufs
        self._accum_key = key
        self._accum_i = 0

    def _accum_compiled_step(self, args, batch_size):
        """One microbatch of an accumulation window: dispatch the grad
        program (adds this microbatch's scaled grads into the donated
        accumulators); the window-FINAL microbatch also dispatches the
        fused update program — exactly ``accum_steps + 1`` dispatches
        and ONE optimizer update (one count bump, one lr read) per
        window.  The AMP gate spans the window: the loss scale holds
        fixed across it (the deferred flag lands only at window close)
        and overflow is detected on the SUMMED grads — an inf/nan from
        any microbatch survives addition."""
        from .gluon import block as _gb
        from .ndarray import ndarray as _ndmod
        from .optimizer import fused as _fused

        tr = self._trainer
        accum = self._accum_steps
        in_leaves, in_struct = _gb._flatten_args(args)
        ctx = in_leaves[0].ctx if in_leaves else current_context()
        flavor = _ndmod._flavor_of(in_leaves)

        prep = self._prep()
        opt, scaler = prep.opt, prep.scaler
        mesh, rep = prep.mesh, prep.rep
        base_sig = self._signature(
            prep, _gb._struct_key(in_struct),
            tuple((tuple(l.shape), l._data.dtype) for l in in_leaves),
            ctx, flavor)
        gsig = ("accum_grad", accum) + base_sig
        usig = self._update_sig(prep, ctx, flavor)
        self._ensure_accum_bufs(prep, usig)

        # the window's scale candidates: every microbatch passes the
        # same (clean, overflow) pair and the same unread previous
        # flag, so the on-device where() selects ONE scale for the
        # whole window and the summed grads equal a big-batch
        # backward's, scaled.  (A mid-window drain() is safe: it
        # resolves the flag to exactly the value the where() selects.)
        lag = _engine.amp_lag() if scaler is not None else 0
        if not lag:
            self.drain()
        if scaler is not None and lag and self._pending_ok is not None:
            s_clean, s_over = scaler.branch_scales()
        elif scaler is not None:
            s_clean = s_over = scaler.loss_scale
        else:
            s_clean = s_over = 1.0
        if self._pending_ok is not None:
            prev_ok = self._pending_ok
        elif mesh is not None:
            prev_ok = jax.device_put(jnp.asarray(True), rep)
        else:
            prev_ok = jnp.asarray(True)

        w_args = [p.data()._data for p in prep.trainable]
        frozen_args = [prep.params[n].data()._data
                       for n in prep.frozen_names]
        if mesh is not None:
            from .parallel import spmd as _spmd

            in_args = [_spmd.put_batch(l._data, mesh) for l in in_leaves]
        else:
            in_args = [l._data for l in in_leaves]
        g_call = (w_args, frozen_args, list(self._accum_bufs), in_args,
                  _random.next_key(),
                  jnp.asarray(s_clean, jnp.float32),
                  jnp.asarray(s_over, jnp.float32), prev_ok)
        grec = self._ensure_program(gsig, prep, in_struct, ctx, flavor,
                                    g_call, kind="grad")
        out_struct, mutated_names = grec.meta
        with self._mesh_ctx(mesh):
            out_raw, mut_vals, new_acc = grec(*g_call)
        self._accum_bufs = list(new_acc)
        for n, v in zip(mutated_names, mut_vals):
            prep.params[n]._data[0]._set_data(v)
        overlap = [n for n in mutated_names if n in prep.slot_of_name]
        if overlap:
            self.fallback_reason = (
                f"forward mutates trainable parameter(s) {overlap}")
        out_nd = [_ndmod._wrap(o, ctx, flavor) for o in out_raw]
        loss = _gb._rebuild_output(out_struct[0], out_nd)

        self._accum_i += 1
        if self._accum_i < accum:
            return loss
        self._accum_i = 0

        # ---- window close: the ONE fused update dispatch ---------------
        indices, group_layout = prep.indices, prep.group_layout
        counts = [opt._index_update_count[i] for i in indices]
        lrs = opt._get_lrs(list(indices))
        wds = opt._get_wds(list(indices))
        scale_val = s_clean
        if scaler is not None:
            tr._amp_original_scale = getattr(
                tr, "_amp_original_scale", tr._scale)
        base = getattr(tr, "_amp_original_scale", tr._scale)
        # the accumulators hold a SUM over accum microbatches of scaled
        # per-microbatch-mean grads; the extra /accum makes the window
        # equal one (accum × batch_size)-batch step's mean
        rescale = base / (scale_val * batch_size * accum)
        rescale_alt = base / (s_over * batch_size * accum)
        lrs_g = [jnp.asarray([lrs[i] for i in m], jnp.float32)
                 for _mp, m in group_layout]
        wds_g = [jnp.asarray([wds[i] for i in m], jnp.float32)
                 for _mp, m in group_layout]
        counts_g = [jnp.asarray([counts[i] for i in m], jnp.float32)
                    for _mp, m in group_layout]
        s_args = tuple(_fused._unwrap(s) for s in prep.states)
        snt = self._sentinel
        want_digest = snt is not None and snt.want_digest()
        if mesh is not None:
            want_arg = jax.device_put(jnp.asarray(want_digest), rep)
        else:
            want_arg = jnp.asarray(want_digest)
        u_call = (w_args, s_args, list(self._accum_bufs),
                  lrs_g, wds_g, counts_g,
                  jnp.asarray(rescale, jnp.float32),
                  jnp.asarray(rescale_alt, jnp.float32),
                  prev_ok, want_arg)
        urec = self._ensure_program(usig, prep, None, ctx, flavor,
                                    u_call, kind="update")
        with self._mesh_ctx(mesh):
            new_w, new_s, new_acc, ok, dig = urec(*u_call)
        self._accum_bufs = list(new_acc)
        if want_digest:
            snt.offer(*dig)
        for p, nw in zip(prep.trainable, new_w):
            p._data[0]._set_data(nw)
        for s, ns in zip(prep.states, new_s):
            _fused._write(s, ns)
        if scaler is not None:
            if lag:
                prev = self._pending_ok
                self._pending_ok = ok
                if prev is not None:
                    _ndmod.count_host_sync()
                    _DEFERRED_READ.inc()
                    # graftlint: disable=host-sync -- the ONE deferred AMP
                    # gate read per window (lagged: never blocks the
                    # current program), counted via count_host_sync
                    overflow = not bool(prev)
                    if overflow:
                        _telemetry.event("amp_overflow", "cached_step",
                                         where="deferred")
                    scaler.update_scale(overflow)
            else:
                _ndmod.count_host_sync()
                # graftlint: disable=host-sync -- the synchronous AMP gate
                # read at window close (MXNET_AMP_LAG=0), counted
                overflow = not bool(ok)
                if overflow:
                    _telemetry.event("amp_overflow", "cached_step",
                                     where="sync")
                scaler.update_scale(overflow)
        return loss

    def _grad_hook(self, slot_of_name):
        """The net-level compiled gradient hook: a net exposing
        ``compiled_grad_transform(named_grads) -> named_grads`` (e.g.
        ``parallel.pipeline.PipelineBlock`` summing tied embed/head
        slices on the packed cotangent) gets it applied INSIDE the
        compiled program, right after the vjp, on both the full-step and
        the accumulation microbatch programs.  Returns ``(slot_names,
        transform)`` — ``(None, None)`` when the net has no hook."""
        gt = getattr(self._net, "compiled_grad_transform", None)
        if gt is None:
            return None, None
        n_slots = (max(slot_of_name.values()) + 1) if slot_of_name else 0
        slot_names: List[Optional[str]] = [None] * n_slots
        for n, i in slot_of_name.items():
            slot_names[i] = n
        return slot_names, gt

    @staticmethod
    def _apply_grad_transform(slot_names, gt, grads):
        if gt is None:
            return grads
        names = list(slot_names) + [None] * (len(grads) - len(slot_names))
        named = {n: g for n, g in zip(names, grads) if n is not None}
        named = gt(named)
        return [named.get(n, g) if n is not None else g
                for n, g in zip(names, grads)]

    @staticmethod
    def _fold_aux(auxes, heads, scale_eff, has_ok):
        """Fold recorded MoE load-balance aux losses into the
        differentiated heads as ONE extra (scaled) head — seeded with a
        unit cotangent like every head, so ``aux_weight * d(aux)``
        reaches the grads/optimizer while the user-visible loss outputs
        stay untouched."""
        if not auxes:
            return heads
        aux_w = float(_config.get("MXNET_MOE_AUX_WEIGHT"))
        at = auxes[0]
        for a in auxes[1:]:
            at = at + a
        at = (at * aux_w).astype(jnp.float32)
        return list(heads) + [at * scale_eff if has_ok else at]

    def _build_grad_program(self, params, names, in_struct, ctx, flavor,
                            slot_of_name, frozen_names, has_ok, donate):
        """The accumulation-window microbatch program: forward + vjp
        only, adding this microbatch's (scaled) grads into the DONATED
        accumulator buffers — no optimizer math, no state touched."""
        from .gluon import block as _gb

        from .parallel import moe as _moe

        net, loss_fn = self._net, self._loss_fn
        raw_fwd, out_struct, mutated_names = _gb._stage_fn(
            lambda *call_args: loss_fn(net, *call_args),
            params, names, in_struct, True, ctx, flavor)
        frozen_pos = {n: j for j, n in enumerate(frozen_names)}
        slot_names, gtrans = self._grad_hook(slot_of_name)

        def grad_fn(w_list, frozen_list, acc_list, in_list, rng_key,
                    scale, scale_alt, prev_ok):
            _pstore.count_trace("train_step")
            if has_ok:
                scale_eff = jnp.where(prev_ok, scale, scale_alt)
            else:
                scale_eff = scale

            def fwd(w_l):
                full = [w_l[slot_of_name[n]] if n in slot_of_name
                        else frozen_list[frozen_pos[n]] for n in names]
                with _moe.aux_scope() as auxes:
                    outs, muts = raw_fwd(full, in_list, rng_key)
                heads = [o * scale_eff for o in outs] if has_ok \
                    else list(outs)
                heads = self._fold_aux(auxes, heads, scale_eff, has_ok)
                return heads, (outs, muts)

            heads, vjp_fn, (outs, muts) = jax.vjp(
                fwd, list(w_list), has_aux=True)
            cts = [jnp.ones(h.shape, h.dtype) for h in heads]
            (grads,) = vjp_fn(cts)
            grads = [g.astype(w.dtype) if g.dtype != w.dtype else g
                     for g, w in zip(grads, w_list)]
            grads = self._apply_grad_transform(slot_names, gtrans, grads)
            new_acc = [a + g for a, g in zip(acc_list, grads)]
            return outs, muts, new_acc

        jitted = jax.jit(grad_fn, donate_argnums=(2,) if donate else ())
        return (jitted, out_struct, mutated_names)

    def _build_update_program(self, group_layout, has_ok, donate):
        """The window-closing program: ONE fused optimizer update from
        the accumulated grads (overflow detected on the SUM), the
        sentinel digest cond, and freshly ZEROED accumulators returned
        in the donated buffers so the next window starts clean."""
        from .optimizer import fused as _fused

        opt = self._trainer._optimizer
        bodies = [_fused.group_step_fn(opt, mp, has_ok)
                  for mp, _m in group_layout]

        def update_fn(w_list, s_list, acc_list, lrs_g, wds_g, counts_g,
                      rescale, rescale_alt, prev_ok, want_digest):
            _pstore.count_trace("train_step")
            if has_ok:
                rescale_eff = jnp.where(prev_ok, rescale, rescale_alt)
            else:
                rescale_eff = rescale
            grads = list(acc_list)
            if has_ok:
                ok = jnp.all(jnp.stack(
                    [jnp.isfinite(g).all() for g in grads])) \
                    if grads else jnp.asarray(True)
            else:
                ok = jnp.asarray(True)
            new_w = list(w_list)
            new_s = list(s_list)
            for gi, (_mp, members) in enumerate(group_layout):
                nw, ns = bodies[gi](
                    [w_list[i] for i in members],
                    [grads[i] for i in members],
                    [s_list[i] for i in members],
                    lrs_g[gi], wds_g[gi], counts_g[gi], rescale_eff, ok)
                for j, i in enumerate(members):
                    new_w[i] = nw[j]
                    new_s[i] = ns[j]
            from . import sentinel as _sentinel

            state_leaves = jax.tree_util.tree_leaves(tuple(new_s))
            dig = jax.lax.cond(
                want_digest,
                lambda: _sentinel.program_digest(new_w, state_leaves,
                                                 grads),
                _sentinel.zero_digest)
            new_acc = [jnp.zeros_like(a) for a in acc_list]
            return new_w, tuple(new_s), new_acc, ok, dig

        return jax.jit(update_fn,
                       donate_argnums=(0, 1, 2) if donate else ())

    def _grad_lower_args(self, prep, in_specs):
        """Abstract lowering args for the microbatch grad program
        (precompile): mirrors :meth:`_lower_args` minus the optimizer
        tail, plus the accumulator buffers."""
        f32 = jax.ShapeDtypeStruct((), jnp.float32)
        mesh = prep.mesh
        if mesh is not None:
            from .parallel import spmd as _spmd

            n_dp = int(mesh.shape.get(_spmd.DATA_AXIS, 1))
            bsh = _spmd.batch_sharding(mesh)

            def _in_spec(s):
                sh = bsh if (s.shape and s.shape[0] % n_dp == 0) \
                    else prep.rep
                return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)

            in_specs = [_in_spec(s) for s in in_specs]
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_,
                                           sharding=prep.rep)
        else:
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_)
        w_args = [p.data()._data for p in prep.trainable]
        frozen_args = [prep.params[n].data()._data
                       for n in prep.frozen_names]
        return (w_args, frozen_args, list(self._accum_bufs),
                list(in_specs), jax.random.PRNGKey(0), f32, f32, prev_ok)

    def _update_lower_args(self, prep):
        """Abstract lowering args for the window-closing update program
        (precompile): real param/state/accumulator buffers, abstract
        per-window scalars."""
        from .optimizer import fused as _fused

        f32 = jax.ShapeDtypeStruct((), jnp.float32)
        if prep.mesh is not None:
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_,
                                           sharding=prep.rep)
            want = jax.ShapeDtypeStruct((), jnp.bool_, sharding=prep.rep)
        else:
            prev_ok = jax.ShapeDtypeStruct((), jnp.bool_)
            want = jax.ShapeDtypeStruct((), jnp.bool_)
        g32 = [jax.ShapeDtypeStruct((len(m),), jnp.float32)
               for _mp, m in prep.group_layout]
        w_args = [p.data()._data for p in prep.trainable]
        s_args = tuple(_fused._unwrap(s) for s in prep.states)
        return (w_args, s_args, list(self._accum_bufs),
                list(g32), list(g32), list(g32), f32, f32, prev_ok, want)

    def _build_program(self, params, names, in_struct, ctx, flavor,
                       slot_of_name, frozen_names, group_layout, has_ok,
                       donate):
        from .gluon import block as _gb
        from .optimizer import fused as _fused

        from .parallel import moe as _moe

        net, loss_fn = self._net, self._loss_fn
        opt = self._trainer._optimizer
        raw_fwd, out_struct, mutated_names = _gb._stage_fn(
            lambda *call_args: loss_fn(net, *call_args),
            params, names, in_struct, True, ctx, flavor)
        bodies = [_fused.group_step_fn(opt, mp, has_ok)
                  for mp, _m in group_layout]
        frozen_pos = {n: j for j, n in enumerate(frozen_names)}
        slot_names, gtrans = self._grad_hook(slot_of_name)

        def step_fn(w_list, s_list, frozen_list, in_list, rng_key,
                    lrs_g, wds_g, counts_g, rescale, scale,
                    scale_alt, rescale_alt, prev_ok, want_digest):
            _pstore.count_trace("train_step")
            # deferred AMP gate: the previous step's flag selects which
            # speculative scale candidate this step really runs with —
            # prev_ok=True (the synchronous gate, or a clean previous
            # step) selects the primary pair bit-exactly via where()
            if has_ok:
                scale_eff = jnp.where(prev_ok, scale, scale_alt)
                rescale_eff = jnp.where(prev_ok, rescale, rescale_alt)
            else:
                scale_eff, rescale_eff = scale, rescale

            def fwd(w_l):
                full = [w_l[slot_of_name[n]] if n in slot_of_name
                        else frozen_list[frozen_pos[n]] for n in names]
                with _moe.aux_scope() as auxes:
                    outs, muts = raw_fwd(full, in_list, rng_key)
                # the loss-scale multiply sits INSIDE the differentiated
                # region so grads come out scaled, exactly like backward
                # on amp.scale_loss's scaled loss
                heads = [o * scale_eff for o in outs] if has_ok \
                    else list(outs)
                heads = self._fold_aux(auxes, heads, scale_eff, has_ok)
                return heads, (outs, muts)

            heads, vjp_fn, (outs, muts) = jax.vjp(
                fwd, list(w_list), has_aux=True)
            cts = [jnp.ones(h.shape, h.dtype) for h in heads]
            (grads,) = vjp_fn(cts)
            grads = [g.astype(w.dtype) if g.dtype != w.dtype else g
                     for g, w in zip(grads, w_list)]
            grads = self._apply_grad_transform(slot_names, gtrans, grads)
            # kvstore 'device'-path reduce: identity for the supported
            # single-replica/single-worker topology (fused into the
            # program by construction; other topologies fell back)
            if has_ok:
                ok = jnp.all(jnp.stack(
                    [jnp.isfinite(g).all() for g in grads])) \
                    if grads else jnp.asarray(True)
            else:
                ok = jnp.asarray(True)
            new_w = list(w_list)
            new_s = list(s_list)
            for gi, (_mp, members) in enumerate(group_layout):
                nw, ns = bodies[gi](
                    [w_list[i] for i in members],
                    [grads[i] for i in members],
                    [s_list[i] for i in members],
                    lrs_g[gi], wds_g[gi], counts_g[gi], rescale_eff, ok)
                for j, i in enumerate(members):
                    new_w[i] = nw[j]
                    new_s[i] = ns[j]
            # training-integrity sentinel: on sentinel-cadence steps the
            # program ALSO emits a state fingerprint of the post-update
            # params + optimizer state + grad norm.  lax.cond keeps the
            # fold off non-sentinel steps at runtime; the flag is a
            # traced arg, so cadence never retraces.  Under the SPMD
            # mesh the fold of replicated values is computed redundantly
            # per device — the per-shard values ARE the per-replica
            # digests the corruption vote compares.
            from . import sentinel as _sentinel

            state_leaves = jax.tree_util.tree_leaves(tuple(new_s))
            dig = jax.lax.cond(
                want_digest,
                lambda: _sentinel.program_digest(new_w, state_leaves,
                                                 grads),
                _sentinel.zero_digest)
            return outs, muts, new_w, tuple(new_s), ok, dig

        # donation aliases the old weight/optimizer-state HBM into the
        # outputs — the whole point of the fused step on chip; CPU has no
        # donation support and would only warn
        jitted = jax.jit(step_fn,
                         donate_argnums=(0, 1) if donate else ())
        return (jitted, out_struct, mutated_names)
