"""Deterministic fault injection + the shared retry/deadline policy.

The reference framework's only robustness story is exception propagation
across the async engine plus a shutdown barrier (SURVEY §5) — every
recovery path was incidental and untestable.  Here the host-side runtime
around the compiled step owns fault absorption, and this module is its
single source of truth:

- :func:`inject` — named fault-injection sites compiled into the runtime
  (``faults.inject("checkpoint.write")``).  Zero overhead when disabled:
  one module-global ``None`` check.  A :class:`FaultPlan` (installed via
  API or the ``MXNET_FAULT_PLAN`` env var, so subprocess tests inject
  deterministically) decides which invocation of which site raises what.
- :func:`retry_call` — the one retry/backoff/deadline policy every
  recovery path shares: deterministic exponential backoff (no jitter —
  tests replay bit-identically), retryable-exception classification
  (:func:`is_retryable`), per-site attempt/failure/retry counters
  (:func:`counters`) and a structured event log (:func:`events`).
  ``retry_call`` runs ``inject(site)`` before every attempt, so wiring a
  site into the runtime and making it recoverable is the same line.

Semantics contract (docs/ROBUSTNESS.md): *pure* operations (pull,
collectives, checkpoint write, download, batch fetch) retry; *mutating*
operations (push with a server-side updater) fail fast — retrying a
half-applied optimizer update is not idempotent.

Every ``inject("<site>")`` string must appear in at least one test —
``tools/check_fault_sites.py`` (run by the suite) enforces it.
"""
from __future__ import annotations

import contextlib
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import config
from . import telemetry as _telemetry

__all__ = [
    "FaultInjected", "TransientFault", "FatalFault", "DeadlineExceeded",
    "ShedError",
    "FaultPlan", "install", "uninstall", "active", "inject", "retry_call",
    "is_retryable", "counters", "events", "record_event", "reset",
    "deadline_scope", "deadline_remaining_us", "deadline_site",
]


class FaultInjected(RuntimeError):
    """Base of every exception raised by an injection site."""


class TransientFault(FaultInjected):
    """Injected fault classified retryable (models preemption / flap)."""


class FatalFault(FaultInjected):
    """Injected fault classified NON-retryable (models a real bug)."""


class DeadlineExceeded(RuntimeError):
    """A retry loop or barrier ran out of wall-clock budget."""


class ShedError(RuntimeError):
    """Typed load-shed refusal (serving admission control, site
    ``serving.admit``; the replica router, site ``router.dispatch``):
    the request was rejected IMMEDIATELY — queue full, KV page pool
    exhausted, the SLO provably unmeetable, the process draining for
    preemption, every replica's circuit breaker open, or the request's
    own deadline budget spent — instead of queueing toward a timeout.
    Overload degrades loudly: callers see this exact type and can back
    off / route elsewhere; they never see a 300 s deadline breach.  NOT
    retryable by default (retrying into an overloaded server amplifies
    the overload).

    ``kind`` tags the refusal reason (``queue`` | ``pool`` | ``slo`` |
    ``draining`` | ``unavailable`` | ``deadline`` | ``None`` for legacy
    raisers) so callers can route on it without parsing the message —
    the machine-readable half of the docs/ROBUSTNESS.md shed contract:
    a ``draining`` shed means this process took a preemption notice
    (retry on another replica or after the restart, never here);
    ``unavailable`` means every serving replica is ejected (breaker
    open / dead) and the router refused rather than hang; ``deadline``
    means the request's ``deadline_us`` budget was exhausted across
    admission + queue + retries + hedges (resubmit with a bigger
    budget, or not at all)."""

    kind: Optional[str] = None

    def __init__(self, *args, kind: Optional[str] = None):
        super().__init__(*args)
        if kind is not None:
            self.kind = kind


# exception kinds a plan spec may name (MXNET_FAULT_PLAN "site:times:kind")
_KINDS: Dict[str, type] = {
    "transient": TransientFault,
    "fatal": FatalFault,
    "oserror": OSError,
    "timeout": TimeoutError,
}


class FaultPlan:
    """Deterministic schedule of injected faults, keyed by site.

    ``fail("ckpt.write", times=2)`` makes invocations 1..2 of that site
    raise :class:`TransientFault`; ``after=N`` shifts the window to
    invocations N+1..N+times.  Counting is per-plan (install a fresh plan
    — or :meth:`reset` — for a fresh schedule) and thread-safe.

    Env form (``MXNET_FAULT_PLAN``), for subprocess tests::

        site[@after]:times[:kind][,site...]   kind in {transient (default),
                                              fatal, oserror, timeout}

    e.g. ``MXNET_FAULT_PLAN="checkpoint.write:1,elastic.step@3:1"``.
    """

    def __init__(self):
        self._rules: Dict[str, List[Dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def fail(self, site: str, times: int = 1, exc: type = TransientFault,
             after: int = 0) -> "FaultPlan":
        if times < 1 or after < 0:
            raise ValueError(f"bad fault rule: times={times} after={after}")
        self._rules.setdefault(site, []).append(
            {"after": after, "times": times, "exc": exc, "seen": 0})
        return self

    @classmethod
    def from_env(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for term in spec.split(","):
            term = term.strip()
            if not term:
                continue
            parts = term.split(":")
            site, after = parts[0], 0
            if "@" in site:
                site, after_s = site.split("@", 1)
                after = int(after_s)
            times = int(parts[1]) if len(parts) > 1 else 1
            kind = parts[2].lower() if len(parts) > 2 else "transient"
            if kind not in _KINDS:
                raise ValueError(
                    f"MXNET_FAULT_PLAN kind {kind!r} unknown "
                    f"(one of {sorted(_KINDS)})")
            plan.fail(site, times=times, exc=_KINDS[kind], after=after)
        return plan

    def sites(self) -> List[str]:
        return sorted(self._rules)

    def reset(self) -> None:
        with self._lock:
            for rules in self._rules.values():
                for r in rules:
                    r["seen"] = 0

    def check(self, site: str) -> None:
        rules = self._rules.get(site)
        if not rules:
            return
        with self._lock:
            fire: Optional[Tuple[type, int]] = None
            for r in rules:
                r["seen"] += 1
                if fire is None and \
                        r["after"] < r["seen"] <= r["after"] + r["times"]:
                    fire = (r["exc"], r["seen"])
        if fire is not None:
            exc, n = fire
            _stats(site).inc("injected")
            record_event(site, "inject", invocation=n, kind=exc.__name__)
            raise exc(f"injected fault at site {site!r} (invocation {n})")


# -- module state ----------------------------------------------------------
_PLAN: Optional[FaultPlan] = None
# per-site counters live in the telemetry registry (family 'faults.site',
# names 'faults.<site>.<attempts|failures|retries|injected>'); _STATS
# caches the site -> CounterGroup views so counters() keeps returning
# plain-int dicts for exactly the sites seen since the last reset()
_STATS: Dict[str, "_telemetry.CounterGroup"] = {}
_EVENTS: "deque" = deque(
    maxlen=max(1, int(config.get("MXNET_FAULT_EVENTS"))))
_STATE_LOCK = threading.Lock()
_sleep = time.sleep          # patch point for tests (no real waiting)


def install(plan: Optional[FaultPlan]) -> None:
    """Install (or, with ``None``, remove) the active plan."""
    global _PLAN
    _PLAN = plan


def uninstall() -> None:
    install(None)


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Scoped installation for tests; restores the previous plan."""
    global _PLAN
    prev, _PLAN = _PLAN, plan
    try:
        yield plan
    finally:
        _PLAN = prev


def inject(site: str) -> None:
    """Fault hook.  ZERO overhead when no plan is installed (one global
    ``None`` check) — safe on per-step hot paths."""
    if _PLAN is not None:
        _PLAN.check(site)


def _stats(site: str) -> "_telemetry.CounterGroup":
    s = _STATS.get(site)
    if s is None:
        with _STATE_LOCK:
            s = _STATS.get(site)
            if s is None:
                s = _STATS[site] = _telemetry.CounterGroup(
                    f"faults.{site}",
                    ("attempts", "failures", "retries", "injected"),
                    doc=f"fault-site {site!r} retry-policy counters",
                    family="faults.site")
                # a re-seen site after reset() starts from zero again
                # (counters() contract: reset forgets every site)
                s.reset()
    return s


def counters(site: Optional[str] = None) -> Dict:
    """Per-site ``{attempts, failures, retries, injected}`` counters
    (views over the telemetry registry, family ``faults.site``)."""
    if site is not None:
        return dict(_stats(site))
    return {k: dict(v) for k, v in _STATS.items()}


def record_event(site: str, action: str, error: Optional[BaseException] = None,
                 **extra) -> None:
    """Append a structured entry to the bounded event log (recovery paths
    outside :func:`retry_call` — e.g. checkpoint-restore degradation —
    log through this too).  Every entry also mirrors onto the telemetry
    event bus (kind ``fault``) where it picks up the current train-step
    index and monotonic timestamp — and, inside a request's
    ``telemetry.trace_scope`` (``retry_call`` runs on the request's own
    thread, so a routed request's retries/deadlines inherit its scope
    ambiently), both copies stamp the request's ``trace_id``."""
    ev: Dict[str, Any] = {"site": site, "action": action, "time": time.time()}
    if error is not None:
        ev["error"] = repr(error)
    trace_id = _telemetry.current_trace()
    if trace_id is not None:
        ev["trace_id"] = trace_id
    ev.update(extra)
    _EVENTS.append(ev)
    _telemetry.event("fault", site, action=action,
                     error=repr(error) if error is not None else None,
                     **extra)


def events(site: Optional[str] = None) -> List[Dict[str, Any]]:
    evs = list(_EVENTS)
    if site is not None:
        evs = [e for e in evs if e.get("site") == site]
    return evs


def reset() -> None:
    """Clear counters + events (and the active plan's invocation counts)."""
    with _STATE_LOCK:
        for g in _STATS.values():
            g.reset()               # zero the registry-backed values too
        _STATS.clear()
    _EVENTS.clear()
    if _PLAN is not None:
        _PLAN.reset()


# -- shared deadline budget -------------------------------------------------
# One wall-clock budget per request, threaded through every nested
# retried site instead of multiplying per-site timeouts: the OUTERMOST
# deadline_scope (or retry_call(deadline_us=)) pins an absolute
# monotonic expiry on this thread; nested scopes can only NARROW it,
# and every retry_call underneath draws backoff from the same remaining
# budget.  Exhaustion raises DeadlineExceeded naming the OUTERMOST
# site — the one whose budget it really was.
_DEADLINE = threading.local()


def _deadline_state() -> Optional[Tuple[float, str]]:
    """(absolute monotonic expiry, outermost site) or None."""
    return getattr(_DEADLINE, "state", None)


def deadline_remaining_us() -> Optional[int]:
    """Microseconds left in this thread's ambient deadline budget
    (negative once spent), or ``None`` when no budget is set.  Queue
    waits and admission checks inside a budget consult this instead of
    inventing their own timeout."""
    st = _deadline_state()
    if st is None:
        return None
    return int((st[0] - time.monotonic()) * 1e6)


def deadline_site() -> Optional[str]:
    """The outermost site that owns the ambient budget (exception
    attribution), or None."""
    st = _deadline_state()
    return None if st is None else st[1]


@contextlib.contextmanager
def deadline_scope(deadline_us: Optional[int] = None, *, site: str,
                   until: Optional[float] = None):
    """Establish (or narrow) the thread's shared deadline budget.

    ``deadline_us`` is relative to now; ``until`` is an absolute
    ``time.monotonic()`` expiry (for carrying ONE request budget across
    threads — stamp the absolute expiry on the request at admission and
    re-enter the scope on whichever thread dispatches it).  An
    enclosing budget that is already tighter wins, and the OUTERMOST
    scope's ``site`` owns every :class:`DeadlineExceeded` raised
    underneath.  With neither argument the scope is a no-op
    passthrough."""
    prev = _deadline_state()
    if until is None:
        if deadline_us is None:
            yield prev
            return
        until = time.monotonic() + deadline_us / 1e6
    if prev is not None:
        until = min(until, prev[0])
        site = prev[1]
    _DEADLINE.state = (until, site)
    try:
        yield _DEADLINE.state
    finally:
        _DEADLINE.state = prev


def _check_deadline(site: str, last_error: Optional[BaseException] = None,
                    about_to_sleep: float = 0.0) -> None:
    """Raise DeadlineExceeded (named after the OUTERMOST site) when the
    ambient budget is spent — or would be spent by sleeping
    ``about_to_sleep`` more seconds."""
    st = _deadline_state()
    if st is None:
        return
    remaining = st[0] - time.monotonic()
    if remaining - about_to_sleep > 0:
        return
    record_event(site, "deadline", last_error,
                 budget_site=st[1], remaining_us=int(remaining * 1e6))
    msg = (f"site {st[1]!r}: shared deadline budget exhausted"
           + (f" at nested site {site!r}" if site != st[1] else "")
           + (f"; last error: {last_error!r}" if last_error is not None
              else ""))
    if last_error is not None:
        raise DeadlineExceeded(msg) from last_error
    raise DeadlineExceeded(msg)


# -- retryable classification ---------------------------------------------
# multiprocessing.TimeoutError subclasses neither OSError nor TimeoutError
import multiprocessing as _mp  # noqa: E402  (stdlib, cheap)

RETRYABLE_DEFAULT: Tuple[type, ...] = (
    TransientFault, OSError, TimeoutError, ConnectionError,
    _mp.TimeoutError, queue.Empty,
)


def is_retryable(exc: BaseException) -> bool:
    """Default classification: transient-looking errors (IO, timeouts,
    injected :class:`TransientFault`) retry; everything else — and any
    :class:`FatalFault` — fails fast."""
    if isinstance(exc, FatalFault):
        return False
    return isinstance(exc, RETRYABLE_DEFAULT)


def retry_call(fn: Callable, *args,
               site: str,
               retries: Optional[int] = None,
               backoff: Optional[float] = None,
               max_backoff: Optional[float] = None,
               deadline: Optional[float] = None,
               deadline_us: Optional[int] = None,
               retryable: Optional[Callable[[BaseException], bool]] = None,
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)`` under the shared retry policy.

    - ``retries``: max re-attempts after the first try (total attempts =
      retries + 1); default ``MXNET_RETRY_MAX``.
    - ``backoff``/``max_backoff``: deterministic exponential delay
      ``min(backoff * 2**(attempt-1), max_backoff)`` between attempts;
      defaults ``MXNET_RETRY_BACKOFF`` / ``MXNET_RETRY_BACKOFF_MAX``.
    - ``deadline``: legacy per-call wall-clock budget (seconds);
      breaching it raises :class:`DeadlineExceeded` chained to the last
      error.
    - ``deadline_us``: the SHARED budget (see :func:`deadline_scope`) —
      one wall clock across this site AND every retried site nested
      under it: each attempt and each backoff sleep draws from the same
      remaining budget (backoff is truncated to it), and exhaustion
      raises :class:`DeadlineExceeded` naming the OUTERMOST site.  An
      ambient scope established by a caller is inherited (and only ever
      narrowed) whether or not this call passes its own value — this is
      what fixes nested-retry timeout multiplication.
    - ``retryable``: predicate overriding :func:`is_retryable`.

    ``inject(site)`` runs before every attempt, so a :class:`FaultPlan`
    targeting ``site`` exercises exactly this recovery path.  After the
    budget is spent the LAST underlying exception re-raises unchanged —
    callers' ``except`` clauses see the same types as without retry.
    """
    with deadline_scope(deadline_us, site=site):
        return _retry_loop(fn, args, kwargs, site, retries, backoff,
                           max_backoff, deadline, retryable, on_retry)


def _retry_loop(fn, args, kwargs, site, retries, backoff, max_backoff,
                deadline, retryable, on_retry):
    retries = config.get("MXNET_RETRY_MAX") if retries is None else retries
    backoff = config.get("MXNET_RETRY_BACKOFF") if backoff is None else backoff
    max_backoff = (config.get("MXNET_RETRY_BACKOFF_MAX")
                   if max_backoff is None else max_backoff)
    check = is_retryable if retryable is None else retryable
    stats = _stats(site)
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        _check_deadline(site)            # budget spent: never attempt
        stats.inc("attempts")
        try:
            inject(site)
            return fn(*args, **kwargs)
        except BaseException as e:
            stats.inc("failures")
            if not check(e) or attempt > retries:
                record_event(site, "raise", e, attempt=attempt)
                raise
            delay = min(backoff * (2 ** (attempt - 1)), max_backoff)
            if deadline is not None and \
                    time.monotonic() - start + delay > deadline:
                record_event(site, "deadline", e, attempt=attempt)
                raise DeadlineExceeded(
                    f"site {site!r}: {deadline}s deadline exceeded after "
                    f"{attempt} attempt(s); last error: {e!r}") from e
            # the SHARED budget: a backoff that would sleep past the
            # remaining budget raises instead (truncation to zero is a
            # loud DeadlineExceeded, never a silent overrun)
            _check_deadline(site, last_error=e, about_to_sleep=delay)
            stats.inc("retries")
            record_event(site, "retry", e, attempt=attempt, delay=delay)
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                _sleep(delay)


# -- env-driven installation (subprocess tests) ----------------------------
_spec = config.get("MXNET_FAULT_PLAN")
if _spec:
    install(FaultPlan.from_env(_spec))
del _spec
