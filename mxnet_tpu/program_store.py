"""One ProgramStore: every compiled XLA executable in the process.

The reference's whole value proposition is ONE ``CachedOp`` cache that
every execution path shares (src/imperative/cached_op.cc).  We instead
grew four disconnected caches — ``cached_step.TrainStep._programs``,
``ServingEngine._programs``, the per-op eager jit cache in
``ndarray.py``, and ``HybridBlock._cached`` — four copy-pasted LRU
record/evict blocks, four counter sets, and NO persistence: bench logs
show 26–98 s per-program XLA compiles paid again on every process
start, elastic recovery, and serving deploy.

This module is the single registry those paths now resolve through:

- **Namespaces** (``train_step`` / ``serving`` / ``hybrid_forward`` /
  ``eager_jit``): each legacy cache becomes a namespace with one shared
  eviction surface and one metrics surface (hits / misses / evictions /
  traces / dispatches, :func:`stats`).  Owners hold a :class:`ScopeCache`
  (an ``OrderedDict`` with counted ``lookup``/``insert``), so a cap
  bounds programs **per owner** — two serving engines can never evict
  each other's steady-state programs.  Caps come from
  ``MXNET_PROGRAM_CACHE_CAPS`` (``"train_step=16,serving=32,..."``),
  falling back to the legacy knobs (``MXNET_COMPILED_STEP_CACHE``,
  ``MXNET_FORWARD_CACHE``) they replace.

- **AOT executables** (:func:`build`): on a cache miss the store traces
  AND compiles ahead of dispatch (``jit(...).lower(args).compile()``)
  and the :class:`Program` record owns the compiled executable —
  dispatch calls it directly, so warm-up from *abstract* shapes
  (``Trainer.precompile`` / ``ServingEngine.warmup``), steady state, and
  elastic restore share ONE code path.  The one prior system that made
  TPU deployment viable did exactly this — compiled artifacts decoupled
  from tracing (TVM, arXiv:1802.04799; Julia→TPU offline full-program
  compilation, arXiv:1810.09868).  A call whose inputs no longer match
  the compiled avals (resharded params after a topology change) falls
  back LOUDLY to the retraceable ``jitted`` callable — counted in
  ``aot_fallbacks``, never silently wrong.  ``MXNET_PROGRAM_AOT=0``
  disables the executables (records keep only the jit callable).

- **Persistence** (``MXNET_PROGRAM_CACHE_DIR``, off by default): backs
  every compile with JAX's persistent compilation cache, keyed by
  (serialized HLO, compile options, jax/jaxlib version) — a second
  process re-tracing the same signature gets a DISK hit (seconds)
  instead of a fresh XLA compile (minutes).  Hit/miss/compile-time
  counters ride on ``jax.monitoring`` (:func:`disk_stats`), so bench
  lanes can show the cold-start tax shrinking.  A corrupted or
  unreadable persistent entry degrades loudly to a fresh recompile
  under the ``program_store.load`` fault site — never a crash.
"""
from __future__ import annotations

import os
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from . import config as _config
from . import faults as _faults
from . import telemetry as _telemetry

__all__ = ["Program", "Namespace", "ScopeCache", "namespace", "scope",
           "build", "count_trace", "stats", "reset_counters", "disk_stats",
           "compile_seconds", "persistent_cache_dir", "version_fingerprint",
           "NAMESPACES"]


def version_fingerprint() -> Tuple[str, str, str]:
    """(jax, jaxlib, backend) — the part of every persistent key that a
    toolchain bump invalidates (JAX folds it into the disk-cache key, so
    a jaxlib upgrade can never resurrect a stale executable; it also
    means disk hits are IMPOSSIBLE across a jaxlib bump — recompile and
    re-warm)."""
    import jaxlib

    return (jax.__version__, jaxlib.__version__, jax.default_backend())


# ---------------------------------------------------------------------------
# Persistent compilation cache: enable + observe
# ---------------------------------------------------------------------------
# Disk-level counters (jax.monitoring): 'hits' = executables deserialized
# from the persistent cache instead of compiled; 'misses' = fresh XLA
# compiles that went through the (enabled) cache and were written back.
# With the cache disabled neither moves.
_DISK = {"hits": 0, "misses": 0, "requests": 0,
         "compile_time_saved_s": 0.0, "retrieval_s": 0.0}
_ENABLED_BY_US = False


def _on_event(event: str, **_kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _DISK["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _DISK["misses"] += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _DISK["requests"] += 1


def _on_duration(event: str, secs: float, **_kw) -> None:
    if event.endswith("compile_time_saved_sec"):
        _DISK["compile_time_saved_s"] += secs
    elif event.endswith("cache_retrieval_time_sec"):
        _DISK["retrieval_s"] += secs


jax.monitoring.register_event_listener(_on_event)
jax.monitoring.register_event_duration_secs_listener(_on_duration)


def _enable_persistent() -> None:
    """Apply MXNET_PROGRAM_CACHE_DIR (off by default, enabled
    per-process).  Runs at import — before any program this framework
    emits compiles — and never overrides a cache dir the user or a
    driver (bench.py) already configured via JAX_COMPILATION_CACHE_DIR."""
    global _ENABLED_BY_US
    d = _config.get("MXNET_PROGRAM_CACHE_DIR")
    if not d or jax.config.jax_compilation_cache_dir is not None:
        return
    jax.config.update("jax_compilation_cache_dir", os.path.expanduser(d))
    # persist EVERYTHING: the parity contract (a warm second process
    # performs 0 fresh compiles) needs even sub-second CPU programs and
    # tiny eager-op executables on disk
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:                     # knob absent on older jax
        pass
    _ENABLED_BY_US = True


_enable_persistent()


def persistent_cache_dir() -> Optional[str]:
    """The live persistent-cache dir (ours, the user's, or None)."""
    return jax.config.jax_compilation_cache_dir


def disk_stats() -> Dict[str, Any]:
    """Persistent-compilation-cache counters for this process."""
    out: Dict[str, Any] = dict(_DISK)
    out["dir"] = persistent_cache_dir()
    out["enabled"] = out["dir"] is not None
    return out


# ---------------------------------------------------------------------------
# Namespaces + per-owner scope caches
# ---------------------------------------------------------------------------
# every Namespace counter lives in the telemetry registry as
# 'program_store.<namespace>.<field>' (family 'program_store.namespace');
# the attribute reads/writes below stay working as properties, so every
# legacy view (cached_step.trace_count, serving.bucket_stats, ...) is now
# transitively a registry view
_NS_FIELDS = ("hits", "misses", "evictions", "traces", "dispatches",
              "aot_fallbacks", "load_degrades", "compile_count")


class Namespace:
    """One metrics + eviction surface shared by every scope of a
    program family (the dispatch-budget gate reads these uniformly)."""

    def __init__(self, name: str, cap_default: int,
                 cap_env: Optional[str] = None):
        self.name = name
        self.cap_default = cap_default
        self.cap_env = cap_env
        self._c = {f: _telemetry.counter(
            f"program_store.{name}.{f}",
            f"ProgramStore namespace {name!r}: {f}",
            family="program_store.namespace") for f in _NS_FIELDS}
        self._c["compile_seconds"] = _telemetry.counter(
            f"program_store.{name}.compile_seconds",
            f"ProgramStore namespace {name!r}: wall-clock building "
            "programs", kind="time", family="program_store.namespace")
        # weakrefs, not strong refs: a dropped owner (a dead TrainStep,
        # a closed engine) must release its programs' HBM
        self._scopes: list = []

    def bump(self, field: str, n=1) -> None:
        """Atomic counter increment (the only write path the store's
        hot paths use)."""
        self._c[field].inc(n)

    def reset(self) -> None:
        for c in self._c.values():
            c.reset()

    def cap(self) -> int:
        """Per-scope program cap: MXNET_PROGRAM_CACHE_CAPS
        ('ns=cap,...') wins, else the legacy knob, else the default."""
        spec = _config.get("MXNET_PROGRAM_CACHE_CAPS") or ""
        for part in spec.split(","):
            k, _, v = part.strip().partition("=")
            if k == self.name and v:
                try:
                    cap = int(v)
                except ValueError:
                    raise ValueError(
                        f"MXNET_PROGRAM_CACHE_CAPS entry {part!r}: cap "
                        "must be an integer")
                if cap < 1:
                    raise ValueError(
                        f"MXNET_PROGRAM_CACHE_CAPS entry {part!r}: cap "
                        "must be >= 1")
                return cap
        if self.cap_env is not None:
            return int(_config.get(self.cap_env))
        return self.cap_default

    def _live_scopes(self):
        scopes = []
        refs = []
        for r in self._scopes:
            s = r()
            if s is not None:
                scopes.append(s)
                refs.append(r)
        self._scopes = refs
        return scopes

    def _attach(self, scope_cache: "ScopeCache") -> None:
        self._live_scopes()                     # prune dead owners
        self._scopes.append(weakref.ref(scope_cache))

    def live(self) -> int:
        """Compiled programs currently held across this namespace's
        live scopes."""
        return sum(len(s) for s in self._live_scopes())

    def stats(self) -> Dict[str, Any]:
        return {
            "hits": self.hits, "misses": self.misses,
            "evictions": self.evictions, "traces": self.traces,
            "dispatches": self.dispatches, "live": self.live(),
            "cap": self.cap(), "aot_fallbacks": self.aot_fallbacks,
            "load_degrades": self.load_degrades,
            "compile_count": self.compile_count,
            "compile_seconds": round(self.compile_seconds, 3),
        }


def _ns_prop(field):
    def _get(self):
        return self._c[field].value

    def _set(self, v):
        self._c[field].set(v)

    return property(_get, _set)


for _f in _NS_FIELDS + ("compile_seconds",):
    setattr(Namespace, _f, _ns_prop(_f))
del _f


class ScopeCache(OrderedDict):
    """One owner's keyspace inside a namespace: an ``OrderedDict`` (so
    existing ``len``/iteration/``clear`` call sites and tests keep
    working) whose ``lookup``/``insert`` route hit/miss/eviction
    accounting through the namespace and enforce its cap — THE single
    implementation of the LRU record/evict block that was previously
    copy-pasted between cached_step.py and serving.py."""

    def __init__(self, ns: Namespace,
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        super().__init__()
        self._ns = ns
        self._on_evict = on_evict
        ns._attach(self)

    @property
    def namespace(self) -> Namespace:
        return self._ns

    def lookup(self, key):
        """Counted get: a hit refreshes LRU recency; a miss is the
        caller's cue to build + ``insert``."""
        rec = self.get(key)
        if rec is None:
            self._ns.bump("misses")
        else:
            self._ns.bump("hits")
            self.move_to_end(key)
        return rec

    def insert(self, key, rec):
        """Record a freshly built program and evict past the namespace
        cap (oldest first)."""
        self[key] = rec
        cap = self._ns.cap()
        while len(self) > cap:
            old_key, old_rec = self.popitem(last=False)
            self._ns.bump("evictions")
            _telemetry.event("cache_evict", self._ns.name, cap=cap)
            if self._on_evict is not None:
                self._on_evict(old_key, old_rec)
        return rec


NAMESPACES: Dict[str, Namespace] = {}


def _declare(name: str, cap_default: int,
             cap_env: Optional[str] = None) -> Namespace:
    ns = NAMESPACES.get(name)
    if ns is None:
        ns = NAMESPACES[name] = Namespace(name, cap_default, cap_env)
    return ns


# the four legacy caches, as namespaces (docs/PERF.md namespace table)
_declare("train_step", 16, cap_env="MXNET_COMPILED_STEP_CACHE")
_declare("serving", 32, cap_env="MXNET_FORWARD_CACHE")
_declare("hybrid_forward", 32, cap_env="MXNET_FORWARD_CACHE")
_declare("eager_jit", 512)
# generative serving (serving_decode.GenerativeEngine): the bounded
# program set is prefill-buckets + 1 decode per engine — the cap only
# needs to cover that grid, and per-owner caps keep co-hosted models
# from evicting each other's decode program
_declare("serving_decode", 32, cap_env="MXNET_FORWARD_CACHE")
# speculative decoding (serving_decode, MXNET_SPEC_DECODE): draft
# prefill buckets + one draft round program + one verify program per
# MXNET_SPEC_K shape — a small fixed grid, kept apart from
# serving_decode so the spec lane's program census is auditable on its
# own (check_dispatch_budget's `spec` lane)
_declare("serving_spec", 32, cap_env="MXNET_FORWARD_CACHE")


def namespace(name: str) -> Namespace:
    try:
        return NAMESPACES[name]
    except KeyError:
        raise KeyError(f"undeclared ProgramStore namespace {name!r}; "
                       f"known: {sorted(NAMESPACES)}")


def scope(name: str,
          on_evict: Optional[Callable[[Any, Any], None]] = None
          ) -> ScopeCache:
    """A new per-owner cache in ``name``'s namespace."""
    return ScopeCache(namespace(name), on_evict)


def count_trace(name: str) -> None:
    """Called from inside a program body: bumps when jax (re)traces it
    (and logs a ``retrace`` bus event with the current step index — a
    steady-state retrace is the classic silent perf killer)."""
    namespace(name).bump("traces")
    _telemetry.event("retrace", name)


# ---------------------------------------------------------------------------
# Programs: build (trace + AOT compile) and dispatch
# ---------------------------------------------------------------------------
class Program:
    """One compiled program record: the AOT executable the store owns
    plus the retraceable ``jitted`` callable behind it, and whatever
    namespace-specific ``meta`` the caller needs at dispatch."""

    __slots__ = ("executable", "jitted", "meta", "_ns")

    def __init__(self, executable, jitted, meta, ns: Namespace):
        self.executable = executable
        self.jitted = jitted
        self.meta = meta
        self._ns = ns

    def __call__(self, *args):
        self._ns.bump("dispatches")
        if self.executable is not None:
            try:
                return self.executable(*args)
            except (TypeError, ValueError) as e:
                # aval/sharding drift vs the compiled signature (both are
                # checked BEFORE execution, so nothing ran and no donated
                # buffer was consumed): fall back to the retraceable
                # callable — loud, counted, never silently wrong.  A
                # genuine error re-raises identically from the jit path.
                self._ns.bump("aot_fallbacks")
                _faults.record_event(
                    "program_store.load", "aot_fallback", e,
                    namespace=self._ns.name)
                self.executable = None
        return self.jitted(*args)


def _aot_enabled() -> bool:
    return bool(_config.get("MXNET_PROGRAM_AOT"))


class _loud_cache_errors:
    """Scoped ``jax_raise_persistent_cache_errors=True``: inside a store
    build a corrupted/unreadable persistent entry must RAISE (so the
    ``program_store.load`` degrade path sees it and logs it) instead of
    jax's default silent skip-and-recompile.  Outside builds the default
    stays False — an eager-op compile hitting a corrupt entry quietly
    recompiles, which is safe there."""

    def __enter__(self):
        self._prev = jax.config.jax_raise_persistent_cache_errors
        jax.config.update("jax_raise_persistent_cache_errors", True)

    def __exit__(self, *exc):
        jax.config.update("jax_raise_persistent_cache_errors", self._prev)


def build(name: str, jitted, lower_args: Tuple, meta: Any = None,
          label: str = "") -> Program:
    """Trace + compile ``jitted`` for ``lower_args`` (concrete arrays
    and/or ``jax.ShapeDtypeStruct`` specs — the latter is what makes
    warm-up from abstract shapes possible) into a :class:`Program`.

    This is the ``program_store.load`` site: with a persistent cache
    enabled the compile step READS disk entries, and a corrupted or
    unreadable entry (or an injected fault) degrades LOUDLY to a fresh
    compile with the disk cache bypassed for this program — recorded in
    ``load_degrades`` + the faults event log, never a crash."""
    ns = namespace(name)
    t0 = time.perf_counter()
    executable = None
    if _aot_enabled():
        try:
            _faults.inject("program_store.load")
            with _loud_cache_errors():
                executable = jitted.lower(*lower_args).compile()
        except Exception as e:
            ns.bump("load_degrades")
            _faults.record_event(
                "program_store.load", "degrade_to_recompile", e,
                namespace=name, label=label,
                cache_dir=persistent_cache_dir())
            cache_dir = persistent_cache_dir()
            if cache_dir is not None:
                # bypass the (possibly corrupt) disk entry and compile
                # fresh; the cache comes back for every later program
                try:
                    jax.config.update("jax_compilation_cache_dir", None)
                    executable = jitted.lower(*lower_args).compile()
                finally:
                    jax.config.update("jax_compilation_cache_dir",
                                      cache_dir)
            else:
                # no persistent entry was in play: this is a real
                # trace/compile failure — the caller's fallback story
                # (eager tape, single-request serving) owns it
                raise
    ns.bump("compile_count")
    ns.bump("compile_seconds", time.perf_counter() - t0)
    return Program(executable, jitted, meta, ns)


def compile_seconds() -> float:
    """Wall-clock spent building programs through the store (all
    namespaces) — the in-process share of the cold-start tax."""
    return sum(ns.compile_seconds for ns in NAMESPACES.values())


def stats(name: Optional[str] = None) -> Dict[str, Any]:
    """The one metrics surface: per-namespace counters + the disk
    cache.  ``stats('train_step')`` returns a single namespace's dict."""
    if name is not None:
        return namespace(name).stats()
    out: Dict[str, Any] = {ns.name: ns.stats()
                           for ns in NAMESPACES.values()}
    out["persistent"] = disk_stats()
    out["compile_seconds"] = round(compile_seconds(), 3)
    return out


def reset_counters(name: Optional[str] = None) -> None:
    """Zero namespace counters (tests/benchmarks); live programs and
    disk-level counters are untouched."""
    if name is not None:
        namespace(name).reset()
        return
    for ns in NAMESPACES.values():
        ns.reset()
