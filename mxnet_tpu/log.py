"""Logging helpers (reference ``python/mxnet/log.py``): a configured
logger factory with level-colored console output when attached to a tty.
"""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING", "ERROR",
           "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

_COLORS = {logging.DEBUG: "\x1b[32m", logging.INFO: "\x1b[34m",
           logging.WARNING: "\x1b[33m", logging.ERROR: "\x1b[31m"}


class _Formatter(logging.Formatter):
    """Level-labeled (and tty-colored) record format, like the
    reference's."""

    def __init__(self, colored: bool):
        super().__init__()
        self._colored = colored

    def format(self, record):
        label = record.levelname[0]
        if self._colored and record.levelno in _COLORS:
            label = _COLORS[record.levelno] + label + "\x1b[0m"
        self._style._fmt = f"{label} %(asctime)s %(process)d %(message)s"
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (reference log.py get_logger): console by
    default, file when ``filename`` given; idempotent per name."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxnet_tpu_init", False):
        logger.setLevel(level)
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler(sys.stderr)
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_Formatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxnet_tpu_init = True
    return logger


getLogger = get_logger
