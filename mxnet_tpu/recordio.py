"""RecordIO: binary record container + image packing.

Reference analog: ``python/mxnet/recordio.py`` + dmlc-core's
``recordio.h`` writer/reader used by ``src/io/iter_image_recordio_2.cc``.
The on-disk format is kept bit-compatible with dmlc RecordIO (magic
``0xced7230a``, length word with a 3-bit continuation flag, 4-byte record
alignment, ``IRHeader`` = ``<IfQQ``) so ``.rec`` shards produced by the
reference's ``tools/im2rec.py`` load here unchanged.
"""
from __future__ import annotations

import numbers
import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xCED7230A
_LFLAG_BITS = 29
_LEN_MASK = (1 << _LFLAG_BITS) - 1


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py:35)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.writable = None
        self.open()

    def open(self, append: bool = False):
        if self.flag == "w":
            # append=True preserves existing records: used when re-opening
            # an already-written shard after fork or unpickle; plain open
            # ('w' / reset()) truncates, matching the reference semantics
            self.handle = open(self.uri, "ab" if append else "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("flag must be 'r' or 'w'")
        self.pid = os.getpid()

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        if self.writable and self.handle is not None:
            self.handle.flush()  # unpickled writers append after this point
        d = dict(self.__dict__)
        d["handle"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        flag = "w" if self.writable else "r"
        self.flag = flag
        self.open(append=self.writable)

    def _check_pid(self):
        # after fork (DataLoader workers) reopen to get a private offset,
        # the reference's pthread_atfork story (src/initialize.cc:71);
        # append mode so a forked writer never truncates the shard
        if self.pid != os.getpid():
            self.close()
            self.open(append=self.writable)

    def write(self, buf: bytes):
        assert self.writable
        self._check_pid()
        upper = _LEN_MASK
        # multi-part encoding for payloads beyond the 29-bit length field
        n = len(buf)
        if n <= upper:
            self._write_chunk(buf, 0)
        else:
            nparts = (n + upper - 1) // upper
            for i in range(nparts):
                part = buf[i * upper:(i + 1) * upper]
                cflag = 1 if i == 0 else (3 if i == nparts - 1 else 2)
                self._write_chunk(part, cflag)

    def _write_chunk(self, buf: bytes, cflag: int):
        self.handle.write(struct.pack("<II", _kMagic,
                                      (cflag << _LFLAG_BITS) | len(buf)))
        self.handle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert not self.writable
        self._check_pid()
        parts = []
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                return b"".join(parts) if parts else None
            magic, lrec = struct.unpack("<II", header)
            if magic != _kMagic:
                raise IOError(f"invalid RecordIO magic {magic:#x} in {self.uri}")
            cflag = lrec >> _LFLAG_BITS
            length = lrec & _LEN_MASK
            data = self.handle.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.handle.read(pad)
            parts.append(data)
            if cflag in (0, 3):
                return b"".join(parts)

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO via a ``key\\tpos`` index file (reference
    recordio.py:146)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self, append: bool = False):
        super().open(append=append)
        if append and self.writable:
            self.fidx = open(self.idx_path, "a")
            return
        self.idx = {}
        self.keys = []
        if self.writable:
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = None
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def __getstate__(self):
        if self.writable and self.fidx is not None:
            self.fidx.flush()
        d = super().__getstate__()
        d["fidx"] = None
        return d

    def seek(self, idx):
        assert not self.writable
        self._check_pid()
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx) -> bytes:
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf: bytes):
        assert self.writable
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{idx}\t{pos}\n")
        self.idx[idx] = pos
        self.keys.append(idx)


# ---------------------------------------------------------------------------
# image record packing (reference recordio.py:207-344)
# ---------------------------------------------------------------------------

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = onp.asarray(label, dtype=onp.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], dtype=onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    import cv2

    ok, buf = cv2.imencode(
        img_fmt, onp.asarray(img),
        [cv2.IMWRITE_JPEG_QUALITY, quality] if img_fmt in (".jpg", ".jpeg")
        else [cv2.IMWRITE_PNG_COMPRESSION, 3])
    if not ok:
        raise IOError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s: bytes, iscolor: int = 1):
    import cv2

    header, img_bytes = unpack(s)
    img = cv2.imdecode(onp.frombuffer(img_bytes, dtype=onp.uint8), iscolor)
    return header, img
