"""Core plumbing shared across the framework.

TPU-native re-design of the reference's ``python/mxnet/base.py`` (ctypes lib
discovery, ``check_call``, handle types).  There is no C library handle here:
the compute substrate is JAX/XLA, so "base" reduces to the error type, the
registry helpers, and small utilities.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

import numpy as _np

__all__ = [
    "MXNetError",
    "NotSupportedForSparseNDArray",
    "string_types",
    "numeric_types",
    "integer_types",
    "env_bool",
    "env_int",
    "env_str",
    "data_dir",
]


def data_dir() -> str:
    """The MXNet cache root (reference mx.base.data_dir): MXNET_HOME or
    ``~/.mxnet``.  model_store/datasets build their subdirs on this."""
    import os

    from . import config

    return os.path.expanduser(config.get("MXNET_HOME"))


_INT32_MAX = 0x7FFFFFFF

# backends whose compiler demotes s64 element types wholesale (measured:
# docs/PERF.md ">int32-scale tensors on chip") — big-dim int64 indexing
# must use the int32-factorized paths there, never device s64
S64_DEMOTING_PLATFORMS = ("tpu", "axon")


def enable_x64(new_val: bool = True):
    """Compat chokepoint for the x64 scope: ``jax.enable_x64`` moved to
    ``jax.experimental`` (removed from the top-level namespace in newer
    jax).  Every honest-int64 path routes through here."""
    import jax

    fn = getattr(jax, "enable_x64", None)
    if fn is None:
        from jax.experimental import enable_x64 as fn
    return fn(new_val)


def s64_demoting_backend() -> bool:
    """True when the CURRENT default backend demotes s64 element types
    wholesale (tpu-class compilers).  Big-dim ops consult this at call
    time to pick between the int32-factorized paths (demoting backends)
    and plain s64 execution (x64-native cpu).  A function, not a constant,
    so tests can monkeypatch it to exercise the factorized machinery on
    the host."""
    import jax

    return jax.default_backend() in S64_DEMOTING_PLATFORMS


def int32_overflow_dim(d) -> bool:
    """True for a CONCRETE dim past int32 range.  Symbolic dims (AOT
    shape-polymorphic export) are never 'big' — comparing them raises
    InconclusiveDimensionOperation.  The single source of truth for the
    >int32 indexing rules in ndarray.py and ops/tensor.py."""
    return isinstance(d, (int, _np.integer)) and d > _INT32_MAX


def pow2_col_factor(n) -> int:
    """Largest power-of-two column factor (<=128) dividing n such that
    BOTH dims of the (n/C, C) view fit int32.  Returns 0 when none
    qualifies (odd n, or n so large that even n/2 overflows int32) —
    callers must refuse rather than pad: padding moves data ALONG the
    big dim, which the TPU runtime corrupts (docs/PERF.md)."""
    for c in (128, 64, 32, 16, 8, 4, 2):
        if n % c == 0 and n // c <= _INT32_MAX:
            return c
    return 0


def bounded_cache_put(cache: dict, key, val, cap: int = 64):
    """Insert into a plain-dict FIFO cache, evicting oldest past cap."""
    cache[key] = val
    while len(cache) > cap:
        cache.pop(next(iter(cache)))
    return val


class MXNetError(RuntimeError):
    """Default error type raised by the framework.

    Mirrors the reference's ``mxnet.base.MXNetError`` (raised from C via
    ``check_call``, ``python/mxnet/base.py``); here errors originate in Python
    or surface from XLA at sync points (see ``ndarray.NDArray.wait_to_read``).
    """


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__(
            f"Function {function.__name__}"
            + (f" (alias {alias})" if alias else "")
            + " is not supported for sparse NDArray"
        )


string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)

_NOTHING = object()


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Read an ``MXNET_*``-style env var (reference: dmlc::GetEnv at
    point of use).  dmlc parity shim for USER code reading arbitrary
    names; in-tree knob reads must go through ``config.declare/get`` so
    docs/ENV_VARS.md stays provably complete (graftlint
    env-discipline)."""
    # graftlint: disable=env-discipline -- user-facing dmlc::GetEnv shim
    return os.environ.get(name, default)


def env_int(name: str, default: int = 0) -> int:
    try:
        # graftlint: disable=env-discipline -- user-facing dmlc shim
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_bool(name: str, default: bool = False) -> bool:
    # graftlint: disable=env-discipline -- user-facing dmlc shim
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() not in ("0", "false", "off", "")


class _ThreadLocalScopeState(threading.local):
    """Small helper for thread-local nested scope flags (autograd, np-shape...)."""

    def __init__(self, **defaults):
        super().__init__()
        self._defaults = dict(defaults)
        for k, v in defaults.items():
            setattr(self, k, v)


class Registry:
    """A minimal name->object registry with alias support.

    Stands in for dmlc::Registry / ``KVStoreBase.register``-style plugin
    registries used throughout the reference.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._store: Dict[str, Any] = {}

    def register(self, name: Optional[str] = None, allow_override: bool = False):
        def _do(obj, key):
            key = key.lower()
            if key in self._store and not allow_override:
                raise ValueError(f"{self.kind} '{key}' already registered")
            self._store[key] = obj
            return obj

        if callable(name):  # used as bare decorator
            obj = name
            return _do(obj, obj.__name__)

        def deco(obj):
            return _do(obj, name or obj.__name__)

        return deco

    def get(self, name: str):
        key = name.lower()
        if key not in self._store:
            raise KeyError(
                f"{self.kind} '{name}' is not registered. "
                f"Available: {sorted(self._store)}"
            )
        return self._store[key]

    def find(self, name: str):
        return self._store.get(name.lower())

    def list(self):
        return sorted(self._store)


def classproperty(func: Callable):
    class _Desc:
        def __get__(self, obj, owner):
            return func(owner)

    return _Desc()
