"""Elementwise operators.

Reference analog: the mshadow_op functor zoo + elemwise_binary/unary op
families (``src/operator/tensor/elemwise_*`` and ``src/operator/mshadow_op.h``).
On TPU these are single jnp calls; XLA fuses chains of them into one kernel,
which is what the reference's pointwise-fusion RTC pass
(``src/operator/fusion/fused_op.cu``) hand-built for CUDA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

# --- binary broadcast ------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: jnp.equal(a, b).astype(a.dtype),
    "not_equal": lambda a, b: jnp.not_equal(a, b).astype(a.dtype),
    "greater": lambda a, b: jnp.greater(a, b).astype(a.dtype),
    "greater_equal": lambda a, b: jnp.greater_equal(a, b).astype(a.dtype),
    "lesser": lambda a, b: jnp.less(a, b).astype(a.dtype),
    "lesser_equal": lambda a, b: jnp.less_equal(a, b).astype(a.dtype),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(a.dtype),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(a.dtype),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(a.dtype),
}

_NONDIFF_BINARY = {
    "equal", "not_equal", "greater", "greater_equal", "lesser", "lesser_equal",
    "logical_and", "logical_or", "logical_xor",
}

for _name, _f in _BINARY.items():
    def _make(f):
        def op(lhs, rhs):
            return f(lhs, rhs)
        return op

    register(
        f"broadcast_{_name}",
        num_inputs=2,
        differentiable=_name not in _NONDIFF_BINARY,
        aliases=[f"elemwise_{_name}"] if _name in ("add", "sub", "mul", "div") else [],
    )(_make(_f))

    def _make_scalar(f):
        def op(data, scalar=0.0, reverse=False):
            s = jnp.asarray(scalar, dtype=data.dtype)
            return f(s, data) if reverse else f(data, s)
        return op

    register(
        f"{_name}_scalar",
        num_inputs=1,
        differentiable=_name not in _NONDIFF_BINARY,
    )(_make_scalar(_f))


# --- unary -----------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
    "isnan": lambda x: jnp.isnan(x),
    "isinf": lambda x: jnp.isinf(x),
    "isfinite": lambda x: jnp.isfinite(x),
}

_NONDIFF_UNARY = {"sign", "rint", "ceil", "floor", "trunc", "fix",
                  "logical_not", "isnan", "isinf", "isfinite"}

for _name, _f in _UNARY.items():
    def _mk(f):
        def op(data):
            return f(data)
        return op

    register(_name, num_inputs=1, differentiable=_name not in _NONDIFF_UNARY)(_mk(_f))


@register("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)
