"""Sampling operators (reference ``src/operator/random/sample_op.cc``).

Each op draws a fresh subkey from the global threefry chain at call time;
under jit-tracing the key is captured as a constant, so Gluon layers that
need per-step randomness (Dropout) thread keys as explicit inputs instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from .. import random as _rng
from .registry import register


def _dt(dtype):
    if dtype in (None, "None"):
        return jnp.float32
    return jnp.dtype(dtype) if isinstance(dtype, str) else dtype


@register("uniform", num_inputs=0, differentiable=False,
          aliases=["random_uniform", "_sample_uniform"], draws_key=True)
def uniform(low=0.0, high=1.0, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.uniform(key, shape, _dt(dtype), minval=low, maxval=high)


@register("normal", num_inputs=0, differentiable=False,
          aliases=["random_normal", "_sample_normal"], draws_key=True)
def normal(loc=0.0, scale=1.0, shape=(1,), dtype=None, key=None):
    if isinstance(scale, (int, float, _onp.floating, _onp.integer)) \
            and float(scale) < 0:
        # reference sample_op validates sigma >= 0 (MXNetError at sync)
        from ..error import MXNetError

        raise MXNetError(f"normal: scale must be non-negative, got {scale}")
    key = key if key is not None else _rng.next_key()
    return loc + scale * jax.random.normal(key, shape, _dt(dtype))


@register("random_gamma", num_inputs=0, differentiable=False,
          aliases=["_sample_gamma"], draws_key=True)
def random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.gamma(key, alpha, shape, _dt(dtype)) * beta


@register("exponential", num_inputs=0, differentiable=False,
          aliases=["random_exponential"], draws_key=True)
def exponential(lam=1.0, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.exponential(key, shape, _dt(dtype)) / lam


@register("poisson", num_inputs=0, differentiable=False, aliases=["random_poisson"], draws_key=True)
def poisson(lam=1.0, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.poisson(key, lam, shape).astype(_dt(dtype))


@register("negative_binomial", num_inputs=0, differentiable=False,
          aliases=["random_negative_binomial"], draws_key=True)
def negative_binomial(k=1, p=1.0, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(_dt(dtype))


@register("randint", num_inputs=0, differentiable=False, aliases=["random_randint"], draws_key=True)
def randint(low=0, high=1, shape=(1,), dtype="int32", key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.randint(key, shape, low, high, _dt(dtype))


@register("randn", num_inputs=0, differentiable=False, draws_key=True)
def randn(shape=(1,), loc=0.0, scale=1.0, dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return loc + scale * jax.random.normal(key, shape, _dt(dtype))


@register("multinomial", num_inputs=1, differentiable=False,
          aliases=["sample_multinomial"], draws_key=True)
def multinomial(data, shape=1, get_prob=False, dtype="int32", key=None):
    key = key if key is not None else _rng.next_key()
    n = shape if isinstance(shape, int) else int(jnp.prod(jnp.asarray(shape)))
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
        if n == 1 and (isinstance(shape, int) and shape == 1):
            out = out[:, 0]
    return out.astype(_dt(dtype))


@register("shuffle", num_inputs=1, differentiable=False, aliases=["_shuffle"], draws_key=True)
def shuffle(data, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.permutation(key, data, axis=0)


@register("bernoulli", num_inputs=0, differentiable=False, draws_key=True)
def bernoulli(prob=0.5, shape=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.bernoulli(key, prob, shape).astype(_dt(dtype))
