"""Contrib operators — transformer fused attention matmuls, detection ops,
resampling (reference ``src/operator/contrib/``).

The interleaved self-attention ops mirror the reference BERT kernels
(``src/operator/contrib/transformer.cc:650-740``): projections stored
interleaved as (qkv) so QK^T and attn*V run as single batched matmuls on the
MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("interleaved_matmul_selfatt_qk", num_inputs=1)
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Input (seq, batch, 3*embed) interleaved per head; output
    (batch*heads, seq, seq) scaled QK^T."""
    qkv = queries_keys_values
    seq, bsz, three_embed = qkv.shape
    embed = three_embed // 3
    head_dim = embed // heads
    x = qkv.reshape(seq, bsz, heads, 3, head_dim)
    q = x[:, :, :, 0, :]  # (seq, bsz, heads, hd)
    k = x[:, :, :, 1, :]
    q = q.transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    k = k.transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    return jnp.matmul(q * scale, k.transpose(0, 2, 1))


@register("interleaved_matmul_selfatt_valatt", num_inputs=2)
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """attention (batch*heads, seq, seq) x V -> (seq, batch, embed)."""
    qkv = queries_keys_values
    seq, bsz, three_embed = qkv.shape
    embed = three_embed // 3
    head_dim = embed // heads
    x = qkv.reshape(seq, bsz, heads, 3, head_dim)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    out = jnp.matmul(attention, v)  # (b*h, seq, hd)
    out = out.reshape(bsz, heads, seq, head_dim).transpose(2, 0, 1, 3)
    return out.reshape(seq, bsz, embed)


@register("interleaved_matmul_encdec_qk", num_inputs=2)
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    seq_q, bsz, embed = queries.shape
    seq_kv = keys_values.shape[0]
    head_dim = embed // heads
    q = queries.reshape(seq_q, bsz, heads, head_dim).transpose(1, 2, 0, 3)
    q = q.reshape(bsz * heads, seq_q, head_dim)
    kv = keys_values.reshape(seq_kv, bsz, heads, 2, head_dim)
    k = kv[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq_kv, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    return jnp.matmul(q * scale, k.transpose(0, 2, 1))


@register("interleaved_matmul_encdec_valatt", num_inputs=2)
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    seq_kv, bsz, two_embed = keys_values.shape
    embed = two_embed // 2
    head_dim = embed // heads
    kv = keys_values.reshape(seq_kv, bsz, heads, 2, head_dim)
    v = kv[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq_kv, head_dim)
    out = jnp.matmul(attention, v)
    seq_q = attention.shape[1]
    out = out.reshape(bsz, heads, seq_q, head_dim).transpose(2, 0, 1, 3)
    return out.reshape(seq_q, bsz, embed)


@register("div_sqrt_dim")
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("boolean_mask", num_inputs=2, differentiable=False)
def boolean_mask(data, index, axis=0):
    # dynamic shape op — returns compacted rows; on TPU callers should prefer
    # masking. Implemented host-side semantics via nonzero with size hint.
    idx = jnp.nonzero(index.astype(bool))[0]
    return jnp.take(data, idx, axis=axis)


@register("index_copy", num_inputs=3, differentiable=False)
def index_copy(old_tensor, index_vector, new_tensor):
    return old_tensor.at[index_vector.astype(jnp.int32)].set(new_tensor)


@register("index_array", num_inputs=1, differentiable=False)
def index_array(data, axes=None):
    shape = data.shape
    axes = tuple(axes) if axes else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    return jnp.stack(grids, axis=-1).astype(jnp.int64)


@register("allclose", num_inputs=2, differentiable=False)
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    return jnp.asarray(
        jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        dtype=jnp.float32,
    )


@register("arange_like", num_inputs=1, differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        out = start + step * jnp.arange(n, dtype=data.dtype)
        return out.reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


@register("quadratic", num_inputs=1)
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """The reference's tutorial op (src/operator/contrib/quadratic_op.cc)."""
    return a * jnp.square(data) + b * data + c


# --- detection / vision contrib -------------------------------------------

@register("BilinearResize2D")
def bilinear_resize2d(data, height=1, width=1, scale_height=None,
                      scale_width=None, mode="size", align_corners=True):
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(round(h * scale_height))
        width = int(round(w * scale_width))
    return jax.image.resize(data, (n, c, height, width), method="bilinear")


@register("AdaptiveAvgPooling2D")
def adaptive_avg_pooling2d(data, output_size=None):
    n, c, h, w = data.shape
    if output_size is None:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    # decompose into reduce_window when divisible, else resize-avg
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        out = jax.lax.reduce_window(
            data, 0.0, jax.lax.add, (1, 1, kh, kw), (1, 1, kh, kw), "VALID"
        )
        return out / (kh * kw)
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


@register("ROIAlign", num_inputs=2)
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROIAlign (reference src/operator/contrib/roi_align.cc) via bilinear
    gather — vectorized over rois."""
    ph, pw = pooled_size
    n, c, h, w = data.shape

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        sr = sample_ratio if sample_ratio > 0 else 2
        ys = y1 + bin_h * (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr)
        xs = x1 + bin_w * (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr)
        ys = ys.reshape(-1)  # (ph*sr,)
        xs = xs.reshape(-1)  # (pw*sr,)
        img = data[batch_idx]  # (c, h, w)
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy1 = ys - y0
        wx1 = xs - x0
        y0 = y0.astype(jnp.int32); x0 = x0.astype(jnp.int32)
        y1i = y1i.astype(jnp.int32); x1i = x1i.astype(jnp.int32)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (
            v00 * ((1 - wy1)[:, None] * (1 - wx1)[None, :])
            + v01 * ((1 - wy1)[:, None] * wx1[None, :])
            + v10 * (wy1[:, None] * (1 - wx1)[None, :])
            + v11 * (wy1[:, None] * wx1[None, :])
        )  # (c, ph*sr, pw*sr)
        val = val.reshape(c, ph, sr, pw, sr).mean(axis=(2, 4))
        return val

    return jax.vmap(one_roi)(rois)


@register("box_iou", num_inputs=2, differentiable=False)
def box_iou(lhs, rhs, format="corner"):
    def to_corner(b):
        if format == "center":
            cx, cy, w2, h2 = b[..., 0], b[..., 1], b[..., 2] / 2, b[..., 3] / 2
            return jnp.stack([cx - w2, cy - h2, cx + w2, cy + h2], axis=-1)
        return b

    a = to_corner(lhs)[..., :, None, :]
    b = to_corner(rhs)[..., None, :, :]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / (area_a + area_b - inter + 1e-12)
