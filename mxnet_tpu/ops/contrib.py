"""Contrib operators — transformer fused attention matmuls, detection ops,
resampling (reference ``src/operator/contrib/``).

The interleaved self-attention ops mirror the reference BERT kernels
(``src/operator/contrib/transformer.cc:650-740``): projections stored
interleaved as (qkv) so QK^T and attn*V run as single batched matmuls on the
MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import enable_x64 as _enable_x64
from .registry import register


@register("interleaved_matmul_selfatt_qk", num_inputs=1)
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Input (seq, batch, 3*embed) interleaved per head; output
    (batch*heads, seq, seq) scaled QK^T."""
    qkv = queries_keys_values
    seq, bsz, three_embed = qkv.shape
    embed = three_embed // 3
    head_dim = embed // heads
    x = qkv.reshape(seq, bsz, heads, 3, head_dim)
    q = x[:, :, :, 0, :]  # (seq, bsz, heads, hd)
    k = x[:, :, :, 1, :]
    q = q.transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    k = k.transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    return jnp.matmul(q * scale, k.transpose(0, 2, 1))


@register("interleaved_matmul_selfatt_valatt", num_inputs=2)
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """attention (batch*heads, seq, seq) x V -> (seq, batch, embed)."""
    qkv = queries_keys_values
    seq, bsz, three_embed = qkv.shape
    embed = three_embed // 3
    head_dim = embed // heads
    x = qkv.reshape(seq, bsz, heads, 3, head_dim)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq, head_dim)
    out = jnp.matmul(attention, v)  # (b*h, seq, hd)
    out = out.reshape(bsz, heads, seq, head_dim).transpose(2, 0, 1, 3)
    return out.reshape(seq, bsz, embed)


@register("interleaved_matmul_encdec_qk", num_inputs=2)
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    seq_q, bsz, embed = queries.shape
    seq_kv = keys_values.shape[0]
    head_dim = embed // heads
    q = queries.reshape(seq_q, bsz, heads, head_dim).transpose(1, 2, 0, 3)
    q = q.reshape(bsz * heads, seq_q, head_dim)
    kv = keys_values.reshape(seq_kv, bsz, heads, 2, head_dim)
    k = kv[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq_kv, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, q.dtype))
    return jnp.matmul(q * scale, k.transpose(0, 2, 1))


@register("interleaved_matmul_encdec_valatt", num_inputs=2)
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    seq_kv, bsz, two_embed = keys_values.shape
    embed = two_embed // 2
    head_dim = embed // heads
    kv = keys_values.reshape(seq_kv, bsz, heads, 2, head_dim)
    v = kv[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(bsz * heads, seq_kv, head_dim)
    out = jnp.matmul(attention, v)
    seq_q = attention.shape[1]
    out = out.reshape(bsz, heads, seq_q, head_dim).transpose(2, 0, 1, 3)
    return out.reshape(seq_q, bsz, embed)


@register("div_sqrt_dim")
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("boolean_mask", num_inputs=2, differentiable=False)
def boolean_mask(data, index, axis=0, size=None):
    """Compact the rows of ``data`` where ``index`` is non-zero (reference
    ``src/operator/contrib/boolean_mask.cc`` — the canonical dynamic-shape
    op, gated by CheckDynamicShapeExists in cached_op.cc:820).

    Dynamic-shape policy on TPU (SURVEY §7 "hard parts"): XLA needs static
    shapes, so inside jit/hybridized graphs the op REQUIRES the
    pad-and-mask contract: pass ``size=k`` (an upper bound on selected
    rows) and the output has static leading size ``k`` — selected rows
    first, in order, then zero padding (same contract as
    ``jnp.nonzero(size=...)``).  Downstream reductions are unaffected by
    the zero rows for sum/mean-style math; pair with ``sum(index)`` when
    the true count matters.  Eagerly (no jit), omitting ``size`` keeps the
    reference's exact compacted-shape semantics.
    """
    mask = index.astype(bool)
    if size is None:
        try:
            idx = jnp.nonzero(mask)[0]
        except jax.errors.ConcretizationTypeError as e:
            from ..base import MXNetError

            raise MXNetError(
                "boolean_mask has a data-dependent output shape and cannot "
                "trace into a jit/hybridized graph without the pad-and-mask "
                "contract: pass size=<max rows> to fix the output's leading "
                "dimension (selected rows first, zero-padded)"
            ) from e
        return jnp.take(data, idx, axis=axis)
    idx = jnp.nonzero(mask, size=int(size), fill_value=data.shape[axis])[0]
    return jnp.take(data, idx, axis=axis, mode="fill", fill_value=0)


@register("index_copy", num_inputs=3, differentiable=False)
def index_copy(old_tensor, index_vector, new_tensor):
    return old_tensor.at[index_vector.astype(jnp.int32)].set(new_tensor)


@register("index_array", num_inputs=1, differentiable=False)
def index_array(data, axes=None):
    shape = data.shape
    axes = tuple(axes) if axes else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    with _enable_x64(True):   # reference index_array emits int64
        return jnp.stack(grids, axis=-1).astype(jnp.int64)


@register("allclose", num_inputs=2, differentiable=False)
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    return jnp.asarray(
        jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        dtype=jnp.float32,
    )


@register("arange_like", num_inputs=1, differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        out = start + step * jnp.arange(n, dtype=data.dtype)
        return out.reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


@register("quadratic", num_inputs=1)
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """The reference's tutorial op (src/operator/contrib/quadratic_op.cc)."""
    return a * jnp.square(data) + b * data + c


# --- detection / vision contrib -------------------------------------------

@register("BilinearResize2D")
def bilinear_resize2d(data, height=1, width=1, scale_height=None,
                      scale_width=None, mode="size", align_corners=True):
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(round(h * scale_height))
        width = int(round(w * scale_width))
    return jax.image.resize(data, (n, c, height, width), method="bilinear")


@register("AdaptiveAvgPooling2D")
def adaptive_avg_pooling2d(data, output_size=None):
    n, c, h, w = data.shape
    if output_size is None:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    # decompose into reduce_window when divisible, else resize-avg
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        out = jax.lax.reduce_window(
            data, 0.0, jax.lax.add, (1, 1, kh, kw), (1, 1, kh, kw), "VALID"
        )
        return out / (kh * kw)
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


@register("ROIAlign", num_inputs=2)
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROIAlign (reference src/operator/contrib/roi_align.cc) via bilinear
    gather — vectorized over rois."""
    ph, pw = pooled_size
    n, c, h, w = data.shape

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        sr = sample_ratio if sample_ratio > 0 else 2
        ys = y1 + bin_h * (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr)
        xs = x1 + bin_w * (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr)
        ys = ys.reshape(-1)  # (ph*sr,)
        xs = xs.reshape(-1)  # (pw*sr,)
        img = data[batch_idx]  # (c, h, w)
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy1 = ys - y0
        wx1 = xs - x0
        y0 = y0.astype(jnp.int32); x0 = x0.astype(jnp.int32)
        y1i = y1i.astype(jnp.int32); x1i = x1i.astype(jnp.int32)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (
            v00 * ((1 - wy1)[:, None] * (1 - wx1)[None, :])
            + v01 * ((1 - wy1)[:, None] * wx1[None, :])
            + v10 * (wy1[:, None] * (1 - wx1)[None, :])
            + v11 * (wy1[:, None] * wx1[None, :])
        )  # (c, ph*sr, pw*sr)
        val = val.reshape(c, ph, sr, pw, sr).mean(axis=(2, 4))
        return val

    return jax.vmap(one_roi)(rois)


@register("box_iou", num_inputs=2, differentiable=False)
def box_iou(lhs, rhs, format="corner"):
    def to_corner(b):
        if format == "center":
            cx, cy, w2, h2 = b[..., 0], b[..., 1], b[..., 2] / 2, b[..., 3] / 2
            return jnp.stack([cx - w2, cy - h2, cx + w2, cy + h2], axis=-1)
        return b

    a = to_corner(lhs)[..., :, None, :]
    b = to_corner(rhs)[..., None, :, :]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / (area_a + area_b - inter + 1e-12)


# ---------------------------------------------------------------------------
# Spatial transform family (reference src/operator/spatial_transformer.cc,
# bilinear_sampler.cc, grid_generator.cc) — all fully differentiable.
# ---------------------------------------------------------------------------

def _bilinear_sample_2d(img, gx, gy):
    """Sample img [C,H,W] at normalized grid coords gx/gy [-1,1] of shape
    [Ho,Wo]; zero padding outside (matches reference BilinearSampler)."""
    C, H, W = img.shape
    x = (gx + 1.0) * (W - 1) / 2.0
    y = (gy + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx1 = x - x0
    wy1 = y - y0

    def gather(yi, xi):
        inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]                   # [C,Ho,Wo]
        return jnp.where(inb[None], v, 0.0)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    return (v00 * (1 - wy1) * (1 - wx1) + v01 * (1 - wy1) * wx1
            + v10 * wy1 * (1 - wx1) + v11 * wy1 * wx1)


@register("BilinearSampler", num_inputs=2, aliases=["bilinear_sampler"])
def bilinear_sampler(data, grid, cudnn_off=None):
    """data [B,C,H,W] sampled at grid [B,2,Ho,Wo] (channel 0 = x, 1 = y,
    normalized to [-1,1]) -> [B,C,Ho,Wo].  Reference
    src/operator/bilinear_sampler.cc."""
    return jax.vmap(lambda d, g: _bilinear_sample_2d(d, g[0], g[1]))(
        data, grid)


def _affine_grid(theta, Ho, Wo):
    """theta [6] row-major 2x3 -> normalized sampling grid [2,Ho,Wo]."""
    t = theta.reshape(2, 3)
    ys = jnp.linspace(-1.0, 1.0, Ho)
    xs = jnp.linspace(-1.0, 1.0, Wo)
    xg, yg = jnp.meshgrid(xs, ys)            # [Ho,Wo]
    ones = jnp.ones_like(xg)
    coords = jnp.stack([xg, yg, ones], axis=0).reshape(3, -1)
    out = t @ coords                          # [2, Ho*Wo]
    return out.reshape(2, Ho, Wo)


@register("GridGenerator", num_inputs=1, aliases=["grid_generator"])
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Generate a BilinearSampler grid (reference grid_generator.cc).

    - affine: data [B,6] affine params -> grid [B,2,Ho,Wo]
    - warp: data [B,2,H,W] pixel flow field added to the identity grid,
      normalized to [-1,1]
    """
    if transform_type == "affine":
        Ho, Wo = int(target_shape[0]), int(target_shape[1])
        return jax.vmap(lambda th: _affine_grid(th, Ho, Wo))(data)
    if transform_type == "warp":
        B, _, H, W = data.shape
        xs = jnp.arange(W, dtype=data.dtype)
        ys = jnp.arange(H, dtype=data.dtype)
        xg, yg = jnp.meshgrid(xs, ys)
        gx = (xg[None] + data[:, 0]) * 2.0 / jnp.maximum(W - 1, 1) - 1.0
        gy = (yg[None] + data[:, 1]) * 2.0 / jnp.maximum(H - 1, 1) - 1.0
        return jnp.stack([gx, gy], axis=1)
    raise ValueError(f"unknown transform_type {transform_type}")


@register("SpatialTransformer", num_inputs=2,
          aliases=["spatial_transformer"])
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """Affine spatial transformer network op (reference
    spatial_transformer.cc): loc [B,6] -> affine grid -> bilinear sample."""
    assert transform_type == "affine" and sampler_type == "bilinear"
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register("DeformableConvolution", num_inputs=-1,
          aliases=["deformable_convolution"])
def deformable_convolution(arrays, kernel=(3, 3), stride=(1, 1),
                           dilate=(1, 1), pad=(0, 0), num_filter=1,
                           num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=1024, layout=None):
    """Deformable convolution v1 (reference
    src/operator/contrib/deformable_convolution.cc).

    arrays = [data [B,C,H,W], offset [B, 2*kh*kw*ndg, Ho, Wo], weight
    [O, C/g, kh, kw], (bias [O])].  TPU-native lowering: bilinear-sample
    the input at kernel+offset positions (gather; differentiable), then a
    single einsum over (C/g, kh, kw) — the im2col+GEMM split the MXU
    likes.
    """
    data, offset, weight = arrays[0], arrays[1], arrays[2]
    bias = None if no_bias or len(arrays) < 4 else arrays[3]
    return _deform_conv_impl(data, offset, weight, bias, kernel, stride,
                             dilate, pad, num_filter, num_group,
                             num_deformable_group)


def _deform_conv_impl(data, offset, weight, bias, kernel, stride, dilate,
                      pad, num_filter, num_group, ndg, mask=None):
    B, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    O = num_filter
    g = num_group

    # base sampling positions [kh*kw, Ho, Wo]
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = oy[None, :, None] + ky[:, None, None]          # [kh,Ho,1]
    base_x = ox[None, None, :] + kx[:, None, None]          # [kw,1,Wo]
    base_y = jnp.broadcast_to(base_y[:, None], (kh, kw, Ho, Wo))
    base_x = jnp.broadcast_to(base_x[None, :, :, :], (kh, kw, Ho, Wo))

    def sample_one(dat, off, msk):
        # dat [C,H,W]; off [2*kh*kw*ndg, Ho, Wo] layout: per deform group,
        # per kernel point, (dy, dx); msk [ndg*kh*kw, Ho, Wo] or None
        off = off.reshape(ndg, kh * kw, 2, Ho, Wo)
        if msk is not None:
            msk = msk.reshape(ndg, kh * kw, Ho, Wo)
        cs = C // ndg
        outs = []
        for dg in range(ndg):
            dy = base_y.reshape(kh * kw, Ho, Wo) + off[dg, :, 0]
            dx = base_x.reshape(kh * kw, Ho, Wo) + off[dg, :, 1]
            # normalize to [-1,1] for the shared bilinear sampler
            gx = dx * 2.0 / jnp.maximum(W - 1, 1) - 1.0
            gy = dy * 2.0 / jnp.maximum(H - 1, 1) - 1.0
            sub = dat[dg * cs:(dg + 1) * cs]
            # sample all kernel points: [C/ndg, kh*kw, Ho, Wo]
            samp = jax.vmap(
                lambda xg, yg: _bilinear_sample_2d(sub, xg, yg),
                in_axes=(0, 0), out_axes=1)(gx, gy)
            if msk is not None:     # DCNv2 modulation per kernel point
                samp = samp * msk[dg][None]
            outs.append(samp)
        return jnp.concatenate(outs, axis=0)    # [C, kh*kw, Ho, Wo]

    if mask is None:
        cols = jax.vmap(lambda d, o: sample_one(d, o, None))(data, offset)
    else:
        cols = jax.vmap(sample_one)(data, offset, mask)
    cols = cols.reshape(B, g, C // g, kh, kw, Ho, Wo)
    wgt = weight.reshape(g, O // g, C // g, kh, kw)
    out = jnp.einsum("bgchkxy,gochk->bgoxy", cols, wgt,
                     preferred_element_type=jnp.float32)
    out = out.reshape(B, O, Ho, Wo).astype(data.dtype)
    if bias is not None:
        out = out + bias.reshape(1, O, 1, 1)
    return out


@register("ModulatedDeformableConvolution", num_inputs=-1,
          aliases=["modulated_deformable_convolution",
                   "_npx_modulated_deformable_convolution"])
def modulated_deformable_convolution(arrays, kernel=(3, 3), stride=(1, 1),
                                     dilate=(1, 1), pad=(0, 0),
                                     num_filter=1, num_group=1,
                                     num_deformable_group=1, no_bias=False,
                                     workspace=1024, layout=None):
    """Deformable convolution v2 (reference
    src/operator/contrib/modulated_deformable_convolution.cc): v1 sampling
    plus a learned per-sample-point modulation mask.

    arrays = [data, offset [B,2*kh*kw*ndg,Ho,Wo], mask [B,kh*kw*ndg,Ho,Wo]
    (already sigmoided by the layer), weight, (bias)].
    """
    data, offset, mask, weight = arrays[0], arrays[1], arrays[2], arrays[3]
    bias = None if no_bias or len(arrays) < 5 else arrays[4]
    return _deform_conv_impl(data, offset, weight, bias, kernel, stride,
                             dilate, pad, num_filter, num_group,
                             num_deformable_group, mask=mask)


# ---------------------------------------------------------------------------
# FFT + count_sketch (reference src/operator/contrib/fft.cc, ifft.cc,
# count_sketch.cc — cuFFT-based there, jnp.fft on TPU here)
# ---------------------------------------------------------------------------

@register("fft")
def fft(data, compute_size=128):
    """Batched 1D FFT of real input [..., d] -> [..., 2*d] with real/imag
    interleaved (reference fft-inl.h:80-130 output layout)."""
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([c.real, c.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        data.dtype)


@register("ifft")
def ifft(data, compute_size=128):
    """Inverse of :func:`fft`: [..., 2*d] interleaved -> [..., d] real.
    Like cuFFT (reference ifft.cc), the transform is UNNORMALIZED — scale
    by 1/d to invert ``fft``."""
    d = data.shape[-1] // 2
    x = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    c = jax.lax.complex(x[..., 0], x[..., 1])
    out = jnp.fft.ifft(c, axis=-1).real * d
    return out.astype(data.dtype)


@register("count_sketch", num_inputs=3)
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (reference count_sketch.cc): out[..., h[i]]
    += s[i] * data[..., i]; h in [0, out_dim), s in {+1,-1}."""
    out_dim = int(out_dim)
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    flat = data.reshape(-1, data.shape[-1])
    contrib = flat * sign[None, :]
    out = jnp.zeros((flat.shape[0], out_dim), data.dtype)
    out = out.at[:, idx].add(contrib)
    return out.reshape(data.shape[:-1] + (out_dim,))
