"""DGL graph-sampling operators (reference
``src/operator/contrib/dgl_graph.cc``).

These are minibatch-construction ops: BFS neighbor sampling, induced
subgraphs, adjacency conversion, compaction.  They are inherently
dynamic-shaped and pointer-chasing, so — like ``nonzero`` and the
host-side data iterators — they run in numpy on the host and feed the
device pipeline; the TPU executes the resulting dense minibatch.  Graphs
use this framework's dense graph-container convention (see ``edge_id``):
a (N, N) matrix whose entries hold edge values (0 = no edge).  CSR
containers (``ndarray/sparse.py``) densify at the frontend.

Output contracts follow the reference docs exactly:
- ``dgl_csr_neighbor_uniform_sample(csr, seed...)`` -> per seed array:
  vertices (max_num_vertices+1, last element = actual count), sampled
  sub-graph ((max, max), rows in sampled-vertex order, columns in
  PARENT vertex ids), layer (max, BFS layer per sampled vertex, -1 pad).
- ``..._non_uniform_sample(csr, prob, seed...)`` adds a probability
  output between the sub-graph and the layer.
- ``dgl_subgraph(x, v..., return_mapping)`` -> induced subgraph per
  vertex set (new edge ids 1..k), plus the original-edge-id matrix when
  return_mapping.
- ``dgl_adjacency(x)`` -> float32 0/1 adjacency.
- ``dgl_graph_compact(graph..., varray..., graph_sizes, return_mapping)``
  -> drops the empty tail rows/columns the samplers pad to
  max_num_vertices and renumbers columns into the compacted id space.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import enable_x64 as _enable_x64
from .registry import register

_RNG = onp.random.RandomState(17)


def seed_rng(seed: int) -> None:
    """Reseed the host-side sampling stream (wired to mx.random.seed)."""
    global _RNG
    _RNG = onp.random.RandomState(seed)


def _i64(x):
    with _enable_x64(True):
        return jnp.asarray(onp.asarray(x, onp.int64), dtype=jnp.int64)


def _sample_one(adj, seeds, num_hops, num_neighbor, max_num_vertices,
                prob: Optional[onp.ndarray]):
    n = adj.shape[0]
    layer_of = {}
    order = []
    for s in seeds:
        s = int(s)
        if s not in layer_of and len(order) < max_num_vertices:
            layer_of[s] = 0
            order.append(s)
    sampled_edges = {}          # src -> list of (col, edge_value)
    frontier = list(order)
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            cols = onp.nonzero(adj[v])[0]
            if cols.size == 0:
                continue
            k = min(int(num_neighbor), cols.size)
            if prob is not None:
                p = onp.asarray(prob, onp.float64)[cols]
                total = p.sum()
                if total <= 0:
                    continue
                pick = _RNG.choice(cols.size, size=k, replace=False,
                                   p=p / total)
            else:
                pick = _RNG.choice(cols.size, size=k, replace=False)
            chosen = cols[onp.sort(pick)]
            sampled_edges.setdefault(v, [])
            for c in chosen:
                c = int(c)
                sampled_edges[v].append((c, adj[v, c]))
                if c not in layer_of and len(order) < max_num_vertices:
                    layer_of[c] = hop
                    order.append(c)
                    nxt.append(c)
        frontier = nxt
    vertices = sorted(layer_of)
    count = len(vertices)
    out_v = onp.zeros(max_num_vertices + 1, onp.int64)
    out_v[:count] = vertices
    out_v[-1] = count
    sub = onp.zeros((max_num_vertices, max_num_vertices), adj.dtype)
    for i, v in enumerate(vertices):
        for (c, val) in sampled_edges.get(v, []):
            if c in layer_of:
                sub[i, c] = val
    layers = onp.full(max_num_vertices, -1, onp.int64)
    for i, v in enumerate(vertices):
        layers[i] = layer_of[v]
    probs = None
    if prob is not None:
        probs = onp.zeros(max_num_vertices, onp.float32)
        probs[:count] = onp.asarray(prob, onp.float32)[vertices]
    return out_v, sub, probs, layers


@register("dgl_csr_neighbor_uniform_sample", num_inputs=-1, num_outputs=-1,
          differentiable=False,
          aliases=("_contrib_dgl_csr_neighbor_uniform_sample",))
def dgl_csr_neighbor_uniform_sample(arrays, num_args=0, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100):
    """arrays = [graph, seed_0, seed_1, ...]; 3 outputs per seed array
    (reference dgl_graph.cc:762)."""
    adj = onp.asarray(arrays[0])
    outs = []
    for seed in arrays[1:]:
        v, sub, _p, layers = _sample_one(
            adj, onp.asarray(seed).ravel(), int(num_hops),
            int(num_neighbor), int(max_num_vertices), None)
        outs += [_i64(v), jnp.asarray(sub), _i64(layers)]
    return tuple(outs)


@register("dgl_csr_neighbor_non_uniform_sample", num_inputs=-1,
          num_outputs=-1, differentiable=False,
          aliases=("_contrib_dgl_csr_neighbor_non_uniform_sample",))
def dgl_csr_neighbor_non_uniform_sample(arrays, num_args=0, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100):
    """arrays = [graph, probability, seed_0, ...]; 4 outputs per seed
    array (reference dgl_graph.cc:867)."""
    adj = onp.asarray(arrays[0])
    prob = onp.asarray(arrays[1]).ravel()
    outs = []
    for seed in arrays[2:]:
        v, sub, p, layers = _sample_one(
            adj, onp.asarray(seed).ravel(), int(num_hops),
            int(num_neighbor), int(max_num_vertices), prob)
        outs += [_i64(v), jnp.asarray(sub), jnp.asarray(p), _i64(layers)]
    return tuple(outs)


@register("dgl_subgraph", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_contrib_dgl_subgraph",))
def dgl_subgraph(arrays, num_args=0, return_mapping=False):
    """Induced subgraph per vertex set: new edge ids 1..k in row-major
    order (+ the original-value matrix when return_mapping) — reference
    dgl_graph.cc:1147's documented example."""
    adj = onp.asarray(arrays[0])
    subs, maps = [], []
    for v in arrays[1:]:
        idx = onp.asarray(v, onp.int64).ravel()
        orig = adj[onp.ix_(idx, idx)]
        new = onp.zeros_like(orig)
        eid = 0
        for r in range(orig.shape[0]):
            for c in range(orig.shape[1]):
                if orig[r, c] != 0:
                    eid += 1
                    new[r, c] = eid
        subs.append(jnp.asarray(new))
        maps.append(jnp.asarray(orig))
    return tuple(subs) + (tuple(maps) if return_mapping else ())


@register("dgl_adjacency", num_inputs=1, differentiable=False,
          aliases=("_contrib_dgl_adjacency",))
def dgl_adjacency(data):
    """Edge-id matrix -> float32 0/1 adjacency (dgl_graph.cc:1408)."""
    return (data != 0).astype(jnp.float32)


@register("dgl_graph_compact", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_contrib_dgl_graph_compact",))
def dgl_graph_compact(arrays, num_args=0, return_mapping=False,
                      graph_sizes=()):
    """Drop the samplers' empty pad rows/cols: inputs are
    [graph_0..graph_{k-1}, varray_0..varray_{k-1}] (reference
    dgl_graph.cc:1583).  Row i of a sampled graph belongs to the i-th
    sampled vertex; columns are parent ids — compaction remaps columns
    through the vertex array into the compacted id space."""
    if isinstance(graph_sizes, (int, float)):
        graph_sizes = (int(graph_sizes),)
    k = len(arrays) // 2
    outs, maps = [], []
    for i in range(k):
        g = onp.asarray(arrays[i])
        varray = onp.asarray(arrays[k + i], onp.int64).ravel()
        size = int(graph_sizes[i]) if i < len(graph_sizes) \
            else int(varray[-1])
        vids = varray[:size]
        col_of = {int(v): j for j, v in enumerate(vids)}
        out = onp.zeros((size, size), g.dtype)
        for r in range(size):
            for c in onp.nonzero(g[r])[0]:
                j = col_of.get(int(c))
                if j is not None:
                    out[r, j] = g[r, c]
        outs.append(jnp.asarray(out))
        maps.append(jnp.asarray(out))
    return tuple(outs) + (tuple(maps) if return_mapping else ())
