"""Detection operators: NMS, box transforms, SSD multibox suite.

Reference: ``src/operator/contrib/bounding_box.cc`` (box_nms/box_iou/
bipartite_matching/box_encode/box_decode) and the SSD ops
``multibox_prior.cc`` / ``multibox_target.cc`` / ``multibox_detection.cc``.

TPU-native design: everything is fixed-shape.  The greedy sequential parts
(NMS suppression, bipartite matching, SSD's two-phase anchor matching) are
``lax.scan`` loops over a statically-sized candidate axis carrying boolean
keep/match masks — O(N) scan steps over vectorised [N] or [N,M] updates,
batched with ``jax.vmap``.  Sorting uses XLA's sort; "removed" boxes are
filled with -1 exactly like the reference so downstream consumers see the
same layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_FMT = {"corner": 0, "center": 1, 0: 0, 1: 1}


def _to_corner(b, fmt):
    if _FMT[fmt] == 0:
        return b
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _from_corner(b, fmt):
    if _FMT[fmt] == 0:
        return b
    l, t, r, bt = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(l + r) / 2, (t + bt) / 2, r - l, bt - t], axis=-1)


def _pair_iou(a, b):
    """Pairwise IoU of corner boxes a [N,4] x b [M,4] -> [N,M]."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_single(x, overlap_thresh, valid_thresh, topk, coord_start,
                score_index, id_index, background_id, force_suppress,
                in_format, out_format):
    N = x.shape[0]
    scores = x[:, score_index]
    valid = scores > valid_thresh
    if id_index >= 0 and background_id >= 0:
        valid &= x[:, id_index] != background_id
    order = jnp.argsort(-scores, stable=True)
    xs = x[order]
    valid_s = valid[order]
    if topk > 0:
        # topk counts VALID candidates (reference filters before nms)
        valid_s &= jnp.cumsum(valid_s.astype(jnp.int32)) <= topk
    boxes = _to_corner(xs[:, coord_start:coord_start + 4], in_format)
    iou = _pair_iou(boxes, boxes)
    if id_index >= 0 and not force_suppress:
        same = xs[:, None, id_index] == xs[None, :, id_index]
    else:
        same = jnp.ones((N, N), bool)
    sup = (iou > overlap_thresh) & same

    def body(kept, i):
        hit = jnp.any(kept & sup[i])
        kept = kept.at[i].set(valid_s[i] & ~hit)
        return kept, None

    kept, _ = lax.scan(body, jnp.zeros((N,), bool), jnp.arange(N))
    # compact kept rows to the front, preserving descending-score order
    rank = jnp.argsort(jnp.where(kept, 0, 1), stable=True)
    out = xs[rank]
    keptc = kept[rank]
    coords = _from_corner(
        _to_corner(out[:, coord_start:coord_start + 4], in_format),
        out_format)
    out = lax.dynamic_update_slice(out, coords.astype(out.dtype),
                                   (0, coord_start))
    return jnp.where(keptc[:, None], out, jnp.asarray(-1.0, out.dtype))


@register("box_nms", num_inputs=1, differentiable=False,
          aliases=["box_non_maximum_suppression"])
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Greedy NMS; suppressed boxes are filled with -1 and survivors are
    sorted by descending score (reference bounding_box.cc:41-110)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    out = jax.vmap(lambda b: _nms_single(
        b, overlap_thresh, valid_thresh, int(topk), int(coord_start),
        int(score_index), int(id_index), int(background_id),
        bool(force_suppress), in_format, out_format))(flat)
    return out.reshape(shape)


@register("bipartite_matching", num_inputs=1, num_outputs=2,
          differentiable=False)
def bipartite_matching(data, threshold=1e-12, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a [..., N, M] score matrix
    (reference bounding_box.cc:163-201): repeatedly take the globally best
    unmatched (row, col) pair.  Returns (row->col [..., N], col->row
    [..., M]), -1 for unmatched."""
    shape = data.shape
    N, M = shape[-2:]
    flat = data.reshape((-1, N, M))
    T = min(N, M) if topk < 0 else min(topk, N, M)

    def single(s):
        big = jnp.asarray(-jnp.inf, s.dtype)
        work = -s if is_ascend else s
        ok = (s >= threshold) if not is_ascend else (s <= threshold)
        work = jnp.where(ok, work, big)

        def body(carry, _):
            work, rows, cols = carry
            idx = jnp.argmax(work)
            i, j = idx // M, idx % M
            good = work[i, j] > big
            rows = jnp.where(good, rows.at[i].set(j), rows)
            cols = jnp.where(good, cols.at[j].set(i), cols)
            work = jnp.where(good, work.at[i, :].set(big), work)
            work = jnp.where(good, work.at[:, j].set(big), work)
            return (work, rows, cols), None

        init = (work, jnp.full((N,), -1, jnp.int32),
                jnp.full((M,), -1, jnp.int32))
        (_, rows, cols), _ = lax.scan(body, init, None, length=T)
        return rows, cols

    rows, cols = jax.vmap(single)(flat)
    return (rows.reshape(shape[:-2] + (N,)).astype(data.dtype),
            cols.reshape(shape[:-2] + (M,)).astype(data.dtype))


@register("box_encode", num_inputs=6, differentiable=False)
def box_encode(samples, matches, anchors, refs, means, stds):
    """Encode matched boxes as normalised center offsets
    (reference bounding_box.cc:211-232).  samples [B,N] (+1 pos), matches
    [B,N] gt index, anchors/refs corner boxes."""
    a = _from_corner(anchors, "center")           # [B,N,4] center
    m = jnp.take_along_axis(refs, matches[..., None].astype(jnp.int32),
                            axis=1)               # [B,N,4]
    g = _from_corner(m, "center")
    t = jnp.stack([
        (g[..., 0] - a[..., 0]) / a[..., 2],
        (g[..., 1] - a[..., 1]) / a[..., 3],
        jnp.log(jnp.maximum(g[..., 2], 1e-12) / a[..., 2]),
        jnp.log(jnp.maximum(g[..., 3], 1e-12) / a[..., 3])], axis=-1)
    t = (t - means.reshape(1, 1, 4)) / stds.reshape(1, 1, 4)
    mask = (samples > 0.5)[..., None]
    return jnp.where(mask, t, 0.0), jnp.broadcast_to(
        mask, t.shape).astype(t.dtype)


@register("box_decode", num_inputs=2, differentiable=False)
def box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
               clip=-1.0, format="corner"):
    """Decode center-offset predictions back to boxes
    (reference bounding_box.cc:234-253)."""
    a = _from_corner(_to_corner(anchors, format), "center")
    dx = data[..., 0] * std0 * a[..., 2] + a[..., 0]
    dy = data[..., 1] * std1 * a[..., 3] + a[..., 1]
    dw = jnp.exp(data[..., 2] * std2) * a[..., 2] / 2
    dh = jnp.exp(data[..., 3] * std3) * a[..., 3] / 2
    out = jnp.stack([dx - dw, dy - dh, dx + dw, dy + dh], axis=-1)
    if clip > 0:
        out = jnp.clip(out, 0.0, clip)
    return out


# ---------------------------------------------------------------------------
# SSD multibox suite
# ---------------------------------------------------------------------------

@register("multibox_prior", num_inputs=1, differentiable=False,
          aliases=["MultiBoxPrior"])
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate SSD anchor boxes from a feature map [B,C,H,W] ->
    (1, H*W*(num_sizes+num_ratios-1), 4) corner boxes in [0,1] coords
    (reference multibox_prior.cc:30-70)."""
    H, W = data.shape[-2], data.shape[-1]
    sizes = tuple(float(s) for s in sizes) or (1.0,)
    ratios = tuple(float(r) for r in ratios) or (1.0,)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    # anchor set per location: all sizes at ratio[0], then ratios[1:] at
    # sizes[0] (reference ordering)
    ws, hs = [], []
    r0 = float(ratios[0]) ** 0.5
    for s in sizes:
        ws.append(s * H / W * r0 / 2)
        hs.append(s / r0 / 2)
    for r in ratios[1:]:
        rr = float(r) ** 0.5
        ws.append(sizes[0] * H / W * rr / 2)
        hs.append(sizes[0] / rr / 2)
    ws = jnp.asarray(ws, jnp.float32)       # [A]
    hs = jnp.asarray(hs, jnp.float32)
    cxg, cyg = jnp.meshgrid(cx, cy)         # [H,W]
    cxg = cxg[..., None]                    # [H,W,1]
    cyg = cyg[..., None]
    out = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
    out = out.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _multibox_match_single(iou, gt_valid, overlap_threshold):
    """Two-phase SSD matching on iou [N,M] with gt mask [M].

    Phase 1 (bipartite): each gt greedily grabs its best unmatched anchor.
    Phase 2: remaining anchors take their best gt if iou > threshold.
    Returns (anchor_flags [N] int32: 1 pos / -1 ignore, matches [N] int32,
    match_iou [N]).  Reference multibox_target.cc:106-180.
    """
    N, M = iou.shape
    big = jnp.asarray(-jnp.inf, jnp.float32)
    work = jnp.where(gt_valid[None, :], iou.astype(jnp.float32), big)

    def body(carry, _):
        work, flags, matches = carry
        idx = jnp.argmax(work)
        i, j = idx // M, idx % M
        good = work[i, j] > 1e-6
        flags = jnp.where(good, flags.at[i].set(1), flags)
        matches = jnp.where(good, matches.at[i].set(j), matches)
        work = jnp.where(good, work.at[i, :].set(big), work)
        work = jnp.where(good, work.at[:, j].set(big), work)
        return (work, flags, matches), None

    init = (work, jnp.full((N,), -1, jnp.int32),
            jnp.full((N,), -1, jnp.int32))
    (_, flags, matches), _ = lax.scan(body, init, None, length=M)

    masked_iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(masked_iou, axis=1)
    best_iou = jnp.max(masked_iou, axis=1)
    phase2 = (flags != 1) & (best_iou > overlap_threshold)
    flags = jnp.where(phase2, 1, flags)
    matches = jnp.where(phase2, best_gt.astype(jnp.int32), matches)
    # per-anchor best-gt IoU, used by negative mining's threshold test
    return flags, matches, best_iou


@register("multibox_target", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["MultiBoxTarget"])
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference multibox_target.cc).

    anchor (1,N,4) corner; label (B,M,5+) rows [cls, xmin, ymin, xmax,
    ymax, ...] with cls=-1 padding; cls_pred (B,C,N) raw logits.
    Returns (loc_target (B,N*4), loc_mask (B,N*4), cls_target (B,N)).
    """
    anc = anchor.reshape(-1, 4)
    N = anc.shape[0]
    v = tuple(float(x) for x in variances)

    def single(lab, cls_p):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _pair_iou(anc, gt_boxes)
        flags, matches, match_iou = _multibox_match_single(
            iou, gt_valid, overlap_threshold)
        num_pos = jnp.sum(flags == 1)
        if negative_mining_ratio > 0:
            # hard-negative mining: among anchors with best-iou below the
            # mining threshold, keep those whose background logit is LEAST
            # confident (highest bg softmax prob ranks first for negation)
            logits = cls_p                     # [C, N]
            prob_bg = jax.nn.softmax(logits, axis=0)[0]
            cand = (flags != 1) & (match_iou < negative_mining_thresh)
            want = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                minimum_negative_samples)
            score = jnp.where(cand, -prob_bg, -jnp.inf)
            order = jnp.argsort(-score)       # most-confusing first
            rankpos = jnp.empty_like(order).at[order].set(jnp.arange(N))
            neg = cand & (rankpos < want)
            flags = jnp.where(neg, 0, flags)
        else:
            flags = jnp.where(flags != 1, 0, flags)
        pos = flags == 1
        safe_match = jnp.clip(matches, 0, lab.shape[0] - 1)
        g = gt_boxes[safe_match]               # [N,4]
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        ax = (anc[:, 0] + anc[:, 2]) / 2
        ay = (anc[:, 1] + anc[:, 3]) / 2
        gw = g[:, 2] - g[:, 0]
        gh = g[:, 3] - g[:, 1]
        gx = (g[:, 0] + g[:, 2]) / 2
        gy = (g[:, 1] + g[:, 3]) / 2
        loc = jnp.stack([(gx - ax) / aw / v[0], (gy - ay) / ah / v[1],
                         jnp.log(jnp.maximum(gw, 1e-12) / aw) / v[2],
                         jnp.log(jnp.maximum(gh, 1e-12) / ah) / v[3]],
                        axis=-1)
        loc_target = jnp.where(pos[:, None], loc, 0.0).reshape(-1)
        loc_mask = jnp.where(pos[:, None],
                             jnp.ones((N, 4), loc.dtype), 0.0).reshape(-1)
        cls_t = jnp.where(pos, lab[safe_match, 0] + 1.0,
                          jnp.where(flags == 0, 0.0, float(ignore_label)))
        return loc_target, loc_mask, cls_t

    loc_t, loc_m, cls_t = jax.vmap(single)(label, cls_pred)
    return loc_t.astype(anchor.dtype), loc_m.astype(anchor.dtype), \
        cls_t.astype(anchor.dtype)


@register("multibox_detection", num_inputs=3, differentiable=False,
          aliases=["MultiBoxDetection"])
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode SSD predictions into detections [B,N,6] rows
    [class_id, score, xmin, ymin, xmax, ymax], suppressed rows -1
    (reference multibox_detection.cc:40-120)."""
    anc = anchor.reshape(-1, 4)
    N = anc.shape[0]
    v = tuple(float(x) for x in variances)

    def single(probs, locs):
        # class with best non-background prob per anchor
        C = probs.shape[0]
        bg = int(background_id)
        has_bg = 0 <= bg < C and C > 1
        mask = jnp.full((C, 1), 0.0, probs.dtype)
        if has_bg:
            mask = mask.at[bg].set(-jnp.inf)
        fg = probs + mask
        # output ids are 0-based foreground classes — channel order with
        # the background class removed (reference multibox_detection.cc:125
        # "outputs[i*6] = id - 1" for bg=0; generalized here).  With no
        # background class (background_id=-1) ids are the raw channels.
        am = jnp.argmax(fg, axis=0)
        cid = (jnp.where(am > bg, am - 1, am) if has_bg else am).astype(
            jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score >= threshold
        cid = jnp.where(keep, cid, -1.0)
        lp = locs.reshape(N, 4)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        ax = (anc[:, 0] + anc[:, 2]) / 2
        ay = (anc[:, 1] + anc[:, 3]) / 2
        ox = lp[:, 0] * v[0] * aw + ax
        oy = lp[:, 1] * v[1] * ah + ay
        ow = jnp.exp(lp[:, 2] * v[2]) * aw / 2
        oh = jnp.exp(lp[:, 3] * v[3]) * ah / 2
        boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        rows = jnp.concatenate([cid[:, None], score[:, None], boxes],
                               axis=-1)
        rows = jnp.where(keep[:, None], rows, -1.0)
        return _nms_single(rows, nms_threshold, 0.0, int(nms_topk), 2, 1, 0,
                           -1, bool(force_suppress), "corner", "corner")

    return jax.vmap(single)(cls_prob, loc_pred.reshape(cls_prob.shape[0],
                                                       -1))


@register("mrcnn_mask_target", num_inputs=4, num_outputs=-1,
          differentiable=False, aliases=("_contrib_mrcnn_mask_target",))
def mrcnn_mask_target(rois, gt_masks, matches, cls_targets, num_rois=0,
                      num_classes=1, mask_size=(14, 14), sample_ratio=2,
                      aligned=False):
    """Mask-RCNN training targets (reference
    src/operator/contrib/mrcnn_mask_target-inl.h:46): for every sampled
    ROI, ROIAlign-crop its matched ground-truth mask to ``mask_size`` and
    emit the per-class targets plus the one-hot class weights the mask
    loss multiplies by.  The crop reuses the ROIAlign lowering
    (ops/contrib.py) so sampling semantics live in one place.

    rois (B, N, 4) corner format; gt_masks (B, M, H, W); matches (B, N)
    int index into M; cls_targets (B, N) int class (0 = background).
    Returns (mask_targets (B, N, C, h, w) — the cropped mask in EVERY
    class channel, reference layout — and mask_cls (B, N, C, h, w) with
    one-hot weights, zero for background).
    """
    from .contrib import roi_align

    if num_rois and num_rois > 0:
        rois = rois[:, :num_rois]
        matches = matches[:, :num_rois]
        cls_targets = cls_targets[:, :num_rois]
    B, N = rois.shape[:2]
    mh, mw = mask_size
    C = num_classes

    def per_image(rois_i, masks_i, match_i, cls_i):
        picked = masks_i[match_i.astype(jnp.int32)][:, None]   # (N,1,H,W)
        idx = jnp.arange(N, dtype=rois_i.dtype)[:, None]
        rois5 = jnp.concatenate([idx, rois_i], axis=1)         # (N,5)
        sampled = roi_align(picked, rois5, pooled_size=(mh, mw),
                            spatial_scale=1.0, sample_ratio=sample_ratio,
                            aligned=aligned)[:, 0]             # (N,h,w)
        onehot = jax.nn.one_hot(cls_i.astype(jnp.int32), C,
                                dtype=sampled.dtype)           # (N,C)
        targets = jnp.broadcast_to(sampled[:, None], (N, C, mh, mw))
        weights = jnp.broadcast_to(onehot[:, :, None, None], (N, C, mh, mw))
        bg = jnp.zeros((C,), sampled.dtype).at[0].set(1.0)
        weights = weights * (1.0 - bg)[None, :, None, None]
        return targets, weights

    return jax.vmap(per_image)(rois, gt_masks, matches, cls_targets)
