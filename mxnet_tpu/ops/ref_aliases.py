"""Reference registration-name aliases.

The reference registers contrib ops under ``_contrib_<name>`` and internal
ops under leading-underscore names (SURVEY §2.2); this framework registers
the canonical name and aliases the reference spelling so code written
against the reference's generated namespaces resolves.  Aliases share the
schema — no duplicate implementations.
"""
from __future__ import annotations

from .registry import alias, find_op

_CONTRIB = [
    "AdaptiveAvgPooling2D", "BilinearResize2D", "MultiBoxDetection",
    "MultiBoxPrior", "MultiBoxTarget", "ROIAlign", "allclose", "arange_like",
    "bipartite_matching", "boolean_mask", "box_decode", "box_encode",
    "box_iou", "box_nms", "index_array", "index_copy", "quadratic",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "count_sketch", "fft", "ifft", "DeformableConvolution",
    "quantize", "dequantize", "requantize", "quantized_conv",
    "quantized_fully_connected", "div_sqrt_dim",
]

# reference internal spelling -> canonical name (not _contrib_ prefixed)
_INTERNAL = {
    "_arange": "arange", "_eye": "eye", "_full": "full", "_ones": "ones",
    "_zeros": "zeros", "_zeros_without_dtype": "zeros",
    "_linspace": "linspace", "_sample_multinomial": "multinomial",
    "_ravel_multi_index": "ravel_multi_index",
    "_unravel_index": "unravel_index", "_rnn_param_concat": "concat",
    "_adamw_update": "adamw_update",
}

# reference registers linalg ops with a leading underscore
_LINALG = [
    "gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk",
    "sumlogdiag", "extractdiag", "makediag", "inverse", "det", "slogdet",
    "gelqf",
]

# numpy-op registration spellings (reference src/operator/numpy/* registers
# the np surface as _npi_*/_np_* NNVM names; the surface functions exist
# here under canonical names — these aliases make reference symbol JSON and
# by-name invoke resolve node-for-node)
_NPI = {
    # elementwise binary (np_elemwise_broadcast_op.cc)
    "_npi_add": "broadcast_add", "_npi_subtract": "broadcast_sub",
    "_npi_multiply": "broadcast_mul", "_npi_true_divide": "broadcast_div",
    "_npi_mod": "broadcast_mod", "_npi_power": "broadcast_power",
    "_npi_hypot": "broadcast_hypot",
    "_npi_add_scalar": "add_scalar", "_npi_subtract_scalar": "sub_scalar",
    "_npi_multiply_scalar": "mul_scalar",
    "_npi_true_divide_scalar": "div_scalar",
    "_npi_mod_scalar": "mod_scalar", "_npi_power_scalar": "power_scalar",
    "_npi_bitwise_and": "bitwise_and", "_npi_bitwise_or": "bitwise_or",
    "_npi_bitwise_xor": "bitwise_xor", "_npi_bitwise_not": "bitwise_not",
    "_npi_deg2rad": "radians", "_npi_rad2deg": "degrees",
    "_npi_log": "log", "_npi_ldexp": "ldexp",
    # reductions (np_broadcast_reduce_op_value.cc)
    "_npi_mean": "mean", "_npi_sum": "sum", "_npi_max": "max",
    "_npi_min": "min", "_npi_prod": "prod", "_npi_cumsum": "cumsum",
    "_npi_argmax": "argmax", "_npi_argmin": "argmin",
    "_npi_norm": "np_norm",
    # shape / manipulation (np_matrix_op.cc)
    "_npi_concatenate": "concat", "_npi_stack": "stack",
    "_npi_dot": "dot", "_npi_matmul": "matmul", "_npi_trace": "trace",
    "_npi_transpose": "transpose", "_npi_flip": "flip",
    "_npi_roll": "roll", "_npi_rot90": "rot90",
    "_npi_squeeze": "squeeze", "_np_squeeze": "squeeze",
    "_npi_copy": "_copy", "_np_reshape": "reshape",
    "_npx_reshape": "reshape", "_npi_pad": "pad",
    "_npi_repeats": "repeat", "_npi_unique": "unique",
    "_npi_where": "where", "_npi_diag": "diag",
    "_npi_broadcast_to": "broadcast_to",
    # creation (np_init_op.cc)
    "_npi_zeros": "zeros", "_npi_ones": "ones", "_npi_full": "full",
    "_npi_identity": "identity", "_npi_eye": "eye",
    "_npi_arange": "arange", "_npi_linspace": "linspace",
    "_npi_tril": "tril", "_npi_triu": "triu",
    # linalg (np_laop lanes)
    "_npi_cholesky": "linalg_cholesky", "_npi_eigh": "linalg_eigh",
    "_npi_eigvalsh": "linalg_eigvalsh", "_npi_svd": "linalg_svd",
    "_npi_qr": "linalg_qr", "_npi_solve": "linalg_solve",
    "_npi_lstsq": "linalg_lstsq", "_npi_pinv": "linalg_pinv",
    "_npi_pinv_scalar_rcond": "linalg_pinv",
    "_npi_tensorinv": "linalg_tensorinv",
    "_npi_matrix_rank": "linalg_matrix_rank",
    "_npi_matrix_rank_none_tol": "linalg_matrix_rank",
    # random (numpy/random/*.cc)
    "_npi_normal": "normal", "_npi_normal_n": "normal",
    "_npi_uniform": "uniform", "_npi_uniform_n": "uniform",
    "_npi_gamma": "random_gamma", "_npi_exponential": "exponential",
    "_npi_bernoulli": "bernoulli", "_npi_multinomial": "multinomial",
}

# legacy internal spellings (reference elemwise_binary_broadcast_op*.cc,
# elemwise_binary_scalar_op*.cc register comparison/logical/scalar ops
# under leading-underscore names)
_LEGACY = {
    "_equal": "broadcast_equal", "_not_equal": "broadcast_not_equal",
    "_greater": "broadcast_greater",
    "_greater_equal": "broadcast_greater_equal",
    "_lesser": "broadcast_lesser",
    "_lesser_equal": "broadcast_lesser_equal",
    "_logical_and": "broadcast_logical_and",
    "_logical_or": "broadcast_logical_or",
    "_logical_xor": "broadcast_logical_xor",
    "_maximum": "broadcast_maximum", "_minimum": "broadcast_minimum",
    # reference mx.sym.maximum/minimum (python-level helpers over _maximum)
    "maximum": "broadcast_maximum", "minimum": "broadcast_minimum",
    "_mod": "broadcast_mod", "_power": "broadcast_power",
    "_hypot": "broadcast_hypot", "_grad_add": "elemwise_add",
    "_equal_scalar": "equal_scalar",
    "_not_equal_scalar": "not_equal_scalar",
    "_greater_scalar": "greater_scalar",
    "_greater_equal_scalar": "greater_equal_scalar",
    "_lesser_scalar": "lesser_scalar",
    "_lesser_equal_scalar": "lesser_equal_scalar",
    "_logical_and_scalar": "logical_and_scalar",
    "_logical_or_scalar": "logical_or_scalar",
    "_logical_xor_scalar": "logical_xor_scalar",
    "_maximum_scalar": "maximum_scalar",
    "_minimum_scalar": "minimum_scalar",
    "_plus_scalar": "add_scalar", "_minus_scalar": "sub_scalar",
    "_mul_scalar": "mul_scalar", "_div_scalar": "div_scalar",
    "_mod_scalar": "mod_scalar", "_power_scalar": "power_scalar",
    "_hypot_scalar": "hypot_scalar",
    "_sample_exponential": "exponential", "_sample_poisson": "poisson",
    "_sample_negative_binomial": "negative_binomial",
    "_multi_lamb_update": "multi_lamb_update",
    "_multi_lans_update": "multi_lans_update",
    # cuDNN-dispatch spelling; one BatchNorm lowering here
    "CuDNNBatchNorm": "BatchNorm",
}


def apply() -> None:
    """Install aliases for every canonical op currently registered.
    Idempotent; called again after late registrations (e.g.
    contrib.quantization, imported after the core package to avoid an
    import cycle) so their reference names resolve too."""
    for name in _CONTRIB:
        ref = f"_contrib_{name}"
        if find_op(name) is not None and find_op(ref) is None:
            alias(name, ref)
    for ref, canon in _INTERNAL.items():
        if find_op(canon) is not None and find_op(ref) is None:
            alias(canon, ref)
    for name in _LINALG:
        canon, ref = f"linalg_{name}", f"_linalg_{name}"
        if find_op(canon) is not None and find_op(ref) is None:
            alias(canon, ref)
    for table in (_NPI, _LEGACY):
        for ref, canon in table.items():
            if find_op(canon) is not None and find_op(ref) is None:
                alias(canon, ref)
    # fused RNN op: the reference registers the stateful cuDNN/CPU op as
    # "RNN" (src/operator/rnn.cc:451); the scan lowering here is _rnn_fused
    if find_op("RNN") is None and find_op("_rnn_fused") is not None:
        alias("_rnn_fused", "RNN")


apply()
