"""Reference registration-name aliases.

The reference registers contrib ops under ``_contrib_<name>`` and internal
ops under leading-underscore names (SURVEY §2.2); this framework registers
the canonical name and aliases the reference spelling so code written
against the reference's generated namespaces resolves.  Aliases share the
schema — no duplicate implementations.
"""
from __future__ import annotations

from .registry import alias, find_op

_CONTRIB = [
    "AdaptiveAvgPooling2D", "BilinearResize2D", "MultiBoxDetection",
    "MultiBoxPrior", "MultiBoxTarget", "ROIAlign", "allclose", "arange_like",
    "bipartite_matching", "boolean_mask", "box_decode", "box_encode",
    "box_iou", "box_nms", "index_array", "index_copy", "quadratic",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "count_sketch", "fft", "ifft", "DeformableConvolution",
    "quantize", "dequantize", "requantize", "quantized_conv",
    "quantized_fully_connected",
]

# reference internal spelling -> canonical name (not _contrib_ prefixed)
_INTERNAL = {
    "_arange": "arange", "_eye": "eye", "_full": "full", "_ones": "ones",
    "_zeros": "zeros", "_zeros_without_dtype": "zeros",
    "_linspace": "linspace", "_sample_multinomial": "multinomial",
    "_ravel_multi_index": "ravel_multi_index",
    "_unravel_index": "unravel_index", "_rnn_param_concat": "concat",
    "_adamw_update": "adamw_update",
}

# reference registers linalg ops with a leading underscore
_LINALG = [
    "gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk",
    "sumlogdiag", "extractdiag", "makediag", "inverse", "det", "slogdet",
    "gelqf",
]


def apply() -> None:
    """Install aliases for every canonical op currently registered.
    Idempotent; called again after late registrations (e.g.
    contrib.quantization, imported after the core package to avoid an
    import cycle) so their reference names resolve too."""
    for name in _CONTRIB:
        ref = f"_contrib_{name}"
        if find_op(name) is not None and find_op(ref) is None:
            alias(name, ref)
    for ref, canon in _INTERNAL.items():
        if find_op(canon) is not None and find_op(ref) is None:
            alias(canon, ref)
    for name in _LINALG:
        canon, ref = f"linalg_{name}", f"_linalg_{name}"
        if find_op(canon) is not None and find_op(ref) is None:
            alias(canon, ref)
    # fused RNN op: the reference registers the stateful cuDNN/CPU op as
    # "RNN" (src/operator/rnn.cc:451); the scan lowering here is _rnn_fused
    if find_op("RNN") is None and find_op("_rnn_fused") is not None:
        alias("_rnn_fused", "RNN")


apply()
