"""Numpy-surface operators that close the reference *registration-name* gap.

The np namespace surface (``mx.np``) has dispatched these through jnp since
round 1, but graph paths — reference symbol-JSON import, by-name ``invoke``
through the C ABI, AMP lists — resolve ops by their *registration* names
(reference ``src/operator/numpy/*`` registers ``_npi_*`` / ``_np_*``
spellings, SURVEY §2.2).  This module registers the canonical ops and
aliases every reference spelling, so a reference-generated graph resolves
node-for-node.

Pure-alias mappings for ops that already exist live in ``ref_aliases.py``;
here are only ops that needed a real (if small) implementation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .. import random as _rng
from ..base import enable_x64 as _enable_x64
from .registry import register


def _dt(dtype, default=jnp.float32):
    if dtype in (None, "None"):
        return default
    return jnp.dtype(dtype) if isinstance(dtype, str) else dtype


# ---------------------------------------------------------------------------
# reductions / statistics (reference np_broadcast_reduce_op_value.cc,
# np_moments_op.cc, np_percentile_op.cc)
# ---------------------------------------------------------------------------

@register("std", aliases=("_npi_std",))
def std(data, axis=None, ddof=0, keepdims=False):
    return jnp.std(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register("var", aliases=("_npi_var",))
def var(data, axis=None, ddof=0, keepdims=False):
    return jnp.var(data, axis=axis, ddof=ddof, keepdims=keepdims)


@register("average", num_inputs=-1, aliases=("_npi_average",))
def average(arrays, axis=None, returned=False, weighted=True):
    """average(a[, weights]) (reference np_broadcast_reduce_op_value.cc
    _npi_average)."""
    a = arrays[0]
    w = arrays[1] if len(arrays) > 1 and weighted else None
    if returned:
        avg, wsum = jnp.average(a, axis=axis, weights=w, returned=True)
        return avg, wsum
    return jnp.average(a, axis=axis, weights=w)


@register("percentile", differentiable=False, aliases=("_npi_percentile",))
def percentile(data, q=50.0, axis=None, interpolation="linear",
               keepdims=False):
    q = jnp.asarray(q)
    return jnp.percentile(data, q, axis=axis, method=interpolation,
                          keepdims=keepdims)


@register("all", differentiable=False, aliases=("_npi_all",))
def all_(data, axis=None, keepdims=False):
    return jnp.all(data, axis=axis, keepdims=keepdims)


@register("any", differentiable=False, aliases=("_npi_any",))
def any_(data, axis=None, keepdims=False):
    return jnp.any(data, axis=axis, keepdims=keepdims)


@register("around", aliases=("_npi_around",))
def around(data, decimals=0):
    """np.around: round-half-to-EVEN (banker's rounding)."""
    return jnp.round(data, decimals)


@register("round", differentiable=False)
def round_(data):
    """Legacy nd round: half away from zero (reference mshadow_op.h round),
    unlike np.around's half-to-even."""
    return jnp.sign(data) * jnp.floor(jnp.abs(data) + 0.5)


@register("bincount", differentiable=False, num_inputs=-1,
          aliases=("_npi_bincount",))
def bincount(arrays, minlength=0):
    x = arrays[0].astype(jnp.int32)
    weights = arrays[1] if len(arrays) > 1 else None
    # static length: jnp.bincount needs a bound; use minlength or data max
    length = max(int(minlength), int(jnp.max(x)) + 1 if x.size else 1)
    return jnp.bincount(x, weights=weights, length=length)


@register("diff", aliases=("_npi_diff",))
def diff(data, n=1, axis=-1):
    return jnp.diff(data, n=n, axis=axis)


@register("ediff1d", num_inputs=-1, aliases=("_npi_ediff1d",))
def ediff1d(arrays, to_end=None, to_begin=None):
    out = jnp.ediff1d(arrays[0].ravel())
    parts = []
    if to_begin is not None:
        parts.append(jnp.atleast_1d(jnp.asarray(to_begin, out.dtype)).ravel())
    parts.append(out)
    if to_end is not None:
        parts.append(jnp.atleast_1d(jnp.asarray(to_end, out.dtype)).ravel())
    return jnp.concatenate(parts) if len(parts) > 1 else out


@register("interp", num_inputs=3, differentiable=False,
          aliases=("_npi_interp",))
def interp(x, xp, fp, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


@register("polyval", num_inputs=2, aliases=("_npi_polyval",))
def polyval(p, x):
    return jnp.polyval(p, x)


@register("nan_to_num", aliases=("_npi_nan_to_num",))
def nan_to_num(data, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(data, nan=nan, posinf=posinf, neginf=neginf)


@register("nonzero", differentiable=False,
          aliases=("_npx_nonzero", "_npi_nonzero"))
def nonzero(data):
    """Indices of non-zero elements as an (N, ndim) int64 tensor
    (reference np_nonzero_op.cc; int64 per the npx contract)."""
    idx = onp.argwhere(onp.asarray(data) != 0)
    with _enable_x64(True):
        return jnp.asarray(idx, dtype=jnp.int64)


# ---------------------------------------------------------------------------
# stacking / splitting (reference np_matrix_op.cc)
# ---------------------------------------------------------------------------

@register("hstack", num_inputs=-1, aliases=("_npi_hstack",))
def hstack(arrays):
    return jnp.hstack(arrays)


@register("vstack", num_inputs=-1, aliases=("_npi_vstack", "_np_vstack"))
def vstack(arrays):
    return jnp.vstack(arrays)


@register("dstack", num_inputs=-1, aliases=("_npi_dstack",))
def dstack(arrays):
    return jnp.dstack(arrays)


@register("column_stack", num_inputs=-1, aliases=("_npi_column_stack",))
def column_stack(arrays):
    return jnp.column_stack(arrays)


@register("hsplit", num_outputs=-1, aliases=("_npi_hsplit",))
def hsplit(data, indices_or_sections=1):
    return tuple(jnp.hsplit(data, indices_or_sections))


@register("dsplit", num_outputs=-1, aliases=("_npi_dsplit",))
def dsplit(data, indices_or_sections=1):
    return tuple(jnp.dsplit(data, indices_or_sections))


# ---------------------------------------------------------------------------
# products / linalg (reference np_tensordot_op.cc, np_kron.cc, np_cross.cc,
# np_einsum_op.cc, la_op.cc numpy lanes)
# ---------------------------------------------------------------------------

@register("tensordot", num_inputs=2,
          aliases=("_npi_tensordot", "_npi_tensordot_int_axes"))
def tensordot(a, b, axes=2, a_axes_summed=None, b_axes_summed=None):
    if a_axes_summed is not None and b_axes_summed is not None:
        axes = (tuple(a_axes_summed), tuple(b_axes_summed))
    return jnp.tensordot(a, b, axes=axes)


@register("kron", num_inputs=2, aliases=("_npi_kron",))
def kron(a, b):
    return jnp.kron(a, b)


@register("cross", num_inputs=2, aliases=("_npi_cross",))
def cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    if axis is not None:
        axisa = axisb = axisc = axis
    return jnp.cross(a, b, axisa=axisa, axisb=axisb, axisc=axisc)


@register("einsum", num_inputs=-1, aliases=("_npi_einsum",))
def einsum(arrays, subscripts="", optimize=0):
    return jnp.einsum(subscripts, *arrays)


@register("linalg_eig", num_outputs=2, differentiable=False,
          aliases=("_npi_eig",))
def linalg_eig(data):
    """General eigendecomposition — CPU-only in XLA, so computed on host
    (reference np_eig.cc; same complex-typed contract)."""
    w, v = onp.linalg.eig(onp.asarray(data))
    return jnp.asarray(w), jnp.asarray(v)


@register("linalg_eigvals", differentiable=False, aliases=("_npi_eigvals",))
def linalg_eigvals(data):
    return jnp.asarray(onp.linalg.eigvals(onp.asarray(data)))


@register("linalg_tensorsolve", num_inputs=2, differentiable=False,
          aliases=("_npi_tensorsolve",))
def linalg_tensorsolve(a, b, a_axes=None):
    return jnp.linalg.tensorsolve(a, b, axes=tuple(a_axes) if a_axes else None)


# ---------------------------------------------------------------------------
# creation (reference np_init_op.cc, np_window_op.cc, np_tri*_op.cc)
# ---------------------------------------------------------------------------

@register("logspace", num_inputs=0, differentiable=False,
          aliases=("_npi_logspace",))
def logspace(start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
             dtype=None):
    return jnp.logspace(start, stop, int(num), endpoint=endpoint, base=base,
                        dtype=_dt(dtype))


@register("indices", num_inputs=0, differentiable=False,
          aliases=("_npi_indices",))
def indices(dimensions=(), dtype="int32"):
    return jnp.indices(tuple(int(d) for d in dimensions), dtype=_dt(dtype))


@register("tri", num_inputs=0, differentiable=False, aliases=("_npi_tri",))
def tri(N=1, M=None, k=0, dtype=None):
    return jnp.tri(int(N), None if M in (None, "None") else int(M), int(k),
                   dtype=_dt(dtype))


@register("tril_indices", num_inputs=0, num_outputs=2, differentiable=False,
          aliases=("_npi_tril_indices",))
def tril_indices(n=1, k=0, m=None):
    m = None if m in (None, "None") else int(m)
    r, c = jnp.tril_indices(int(n), int(k), m)
    return r, c


@register("full_like", differentiable=False, aliases=("_npi_full_like",))
def full_like(data, fill_value=0.0, dtype=None):
    return jnp.full_like(data, fill_value,
                         dtype=_dt(dtype, default=data.dtype))


@register("hanning", num_inputs=0, differentiable=False,
          aliases=("_npi_hanning",))
def hanning(M=1, dtype=None):
    return jnp.hanning(int(M)).astype(_dt(dtype))


@register("hamming", num_inputs=0, differentiable=False,
          aliases=("_npi_hamming",))
def hamming(M=1, dtype=None):
    return jnp.hamming(int(M)).astype(_dt(dtype))


@register("blackman", num_inputs=0, differentiable=False,
          aliases=("_npi_blackman",))
def blackman(M=1, dtype=None):
    return jnp.blackman(int(M)).astype(_dt(dtype))


# ---------------------------------------------------------------------------
# manipulation (reference np_matrix_op.cc, np_delete_op.cc, np_insert_op*.cc)
# ---------------------------------------------------------------------------

@register("moveaxis", aliases=("_npi_moveaxis", "_np_moveaxis"))
def moveaxis(data, source=0, destination=0):
    src = (source,) if isinstance(source, int) else tuple(source)
    dst = (destination,) if isinstance(destination, int) \
        else tuple(destination)
    return jnp.moveaxis(data, src, dst)


@register("rollaxis", aliases=("_npi_rollaxis",))
def rollaxis(data, axis=0, start=0):
    return jnp.rollaxis(data, axis, start)


@register("diagonal", aliases=("_npi_diagonal", "_np_diagonal"))
def diagonal(data, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(data, offset=offset, axis1=axis1, axis2=axis2)


@register("diagflat", aliases=("_npi_diagflat",))
def diagflat(data, k=0):
    return jnp.diagflat(data, k=k)


@register("diag_indices_from", differentiable=False, num_outputs=1,
          aliases=("_npi_diag_indices_from",))
def diag_indices_from(data):
    """(ndim, n) index tensor (reference np_matrix_op.cc
    _npi_diag_indices_from packs the tuple into one tensor)."""
    idx = jnp.diag_indices_from(data)
    return jnp.stack(idx, axis=0)


@register("fill_diagonal", differentiable=False,
          aliases=("_npi_fill_diagonal",))
def fill_diagonal(data, val=0.0, wrap=False):
    """Functional fill_diagonal (the reference mutates in place)."""
    a = onp.array(onp.asarray(data), copy=True)
    vals = val if isinstance(val, (list, tuple)) else (val,)
    onp.fill_diagonal(a, vals if len(vals) > 1 else vals[0], wrap=wrap)
    return jnp.asarray(a)


@register("delete", num_inputs=-1, differentiable=False,
          aliases=("_npi_delete",))
def delete(arrays, obj=None, start=None, stop=None, step=None, axis=None):
    """np.delete: ``obj`` int attr, slice attrs (start/stop/step), or a
    second index-array input (reference np_delete_op.cc)."""
    data = arrays[0]
    if len(arrays) > 1:
        obj = onp.asarray(arrays[1]).astype(onp.int64)
    elif start is not None or stop is not None or step is not None:
        obj = slice(start, stop, step)
    return jnp.delete(data, obj, axis=axis,
                      assume_unique_indices=False)


@register("insert", num_inputs=-1, differentiable=False,
          aliases=("_npi_insert_scalar", "_npi_insert_slice",
                   "_npi_insert_tensor"))
def insert(arrays, obj=None, val=None, start=None, stop=None, step=None,
           axis=None):
    """np.insert; values come as a second input tensor or a ``val``
    scalar attr; position as an int attr, slice attrs, or index tensor
    (reference np_insert_op_scalar/slice/tensor.cc)."""
    data = arrays[0]
    rest = list(arrays[1:])
    if val is None and rest:
        values = rest.pop()
    else:
        values = val
    if rest:                       # leading index tensor variant
        obj = onp.asarray(rest[0]).astype(onp.int64)
    elif start is not None or stop is not None or step is not None:
        obj = slice(start, stop, step)
    return jnp.insert(data, obj, values, axis=axis)


@register("atleast_1d", num_inputs=-1, num_outputs=-1,
          aliases=("_npi_atleast_1d",))
def atleast_1d(arrays):
    out = jnp.atleast_1d(*arrays)
    return out if isinstance(out, (list, tuple)) else (out,)


@register("atleast_2d", num_inputs=-1, num_outputs=-1,
          aliases=("_npi_atleast_2d",))
def atleast_2d(arrays):
    out = jnp.atleast_2d(*arrays)
    return out if isinstance(out, (list, tuple)) else (out,)


@register("atleast_3d", num_inputs=-1, num_outputs=-1,
          aliases=("_npi_atleast_3d",))
def atleast_3d(arrays):
    out = jnp.atleast_3d(*arrays)
    return out if isinstance(out, (list, tuple)) else (out,)


@register("share_memory", num_inputs=2, differentiable=False,
          aliases=("_npi_share_memory",))
def share_memory(a, b):
    """Always false: XLA buffers are immutable and never alias across
    distinct arrays (reference np_memory_op.cc)."""
    return jnp.zeros((), dtype=jnp.bool_)


# ---------------------------------------------------------------------------
# binary ufuncs missing as registered names
# (reference np_elemwise_broadcast_op*.cc)
# ---------------------------------------------------------------------------

_NEW_BINARY = {
    "copysign": jnp.copysign,
    "lcm": lambda a, b: jnp.lcm(a.astype(jnp.int32), b.astype(jnp.int32)),
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "fmod": jnp.fmod,
    "arctan2": jnp.arctan2,
}
_NEW_BINARY_NONDIFF = {"lcm"}

for _name, _f in _NEW_BINARY.items():
    def _mk2(f):
        def op(lhs, rhs):
            return f(lhs, rhs)
        return op

    def _mks(f):
        def op(data, scalar=0.0, reverse=False):
            s = jnp.asarray(scalar, dtype=data.dtype)
            return f(s, data) if reverse else f(data, s)
        return op

    def _mkr(f):
        def op(data, scalar=0.0):
            return f(jnp.asarray(scalar, dtype=data.dtype), data)
        return op

    _d = _name not in _NEW_BINARY_NONDIFF
    register(_name, num_inputs=2, differentiable=_d,
             aliases=(f"_npi_{_name}",))(_mk2(_f))
    register(f"{_name}_scalar", num_inputs=1, differentiable=_d,
             aliases=(f"_npi_{_name}_scalar",))(_mks(_f))

register("rfmod_scalar", num_inputs=1,
         aliases=("_npi_rfmod_scalar",))(
    lambda data, scalar=0.0: jnp.fmod(
        jnp.asarray(scalar, dtype=data.dtype), data))
register("rarctan2_scalar", num_inputs=1,
         aliases=("_npi_rarctan2_scalar",))(
    lambda data, scalar=0.0: jnp.arctan2(
        jnp.asarray(scalar, dtype=data.dtype), data))
register("rcopysign_scalar", num_inputs=1,
         aliases=("_npi_rcopysign_scalar",))(
    lambda data, scalar=0.0: jnp.copysign(
        jnp.asarray(scalar, dtype=data.dtype), data))
register("rldexp_scalar", num_inputs=1, aliases=("_npi_rldexp_scalar",))(
    lambda data, scalar=0.0: jnp.ldexp(
        jnp.asarray(scalar, dtype=data.dtype), data.astype(jnp.int32)))
register("ldexp_scalar", num_inputs=1, aliases=("_npi_ldexp_scalar",))(
    lambda data, scalar=0.0: jnp.ldexp(data, jnp.asarray(int(scalar),
                                                         jnp.int32)))


def _bitwise_scalar(f):
    def op(data, scalar=0, reverse=False):
        with _enable_x64(True):
            s = jnp.asarray(int(scalar), dtype=jnp.int64)
            d = data.astype(jnp.int64)
            out = f(s, d) if reverse else f(d, s)
            return out.astype(data.dtype)
    return op


register("bitwise_and_scalar", num_inputs=1, differentiable=False,
         aliases=("_npi_bitwise_and_scalar",))(
    _bitwise_scalar(jnp.bitwise_and))
register("bitwise_or_scalar", num_inputs=1, differentiable=False,
         aliases=("_npi_bitwise_or_scalar",))(
    _bitwise_scalar(jnp.bitwise_or))
register("bitwise_xor_scalar", num_inputs=1, differentiable=False,
         aliases=("_npi_bitwise_xor_scalar",))(
    _bitwise_scalar(jnp.bitwise_xor))


# legacy reversed-scalar ops (reference elemwise_binary_scalar_op_basic.cc
# _rminus_scalar / _rdiv_scalar / _rmod_scalar / _rpower_scalar)
register("rsub_scalar", num_inputs=1,
         aliases=("_rminus_scalar", "_npi_rsubtract_scalar"))(
    lambda data, scalar=0.0: jnp.asarray(scalar, data.dtype) - data)
register("rdiv_scalar", num_inputs=1,
         aliases=("_rdiv_scalar", "_npi_rtrue_divide_scalar"))(
    lambda data, scalar=0.0: jnp.asarray(scalar, data.dtype) / data)
register("rmod_scalar", num_inputs=1,
         aliases=("_rmod_scalar", "_npi_rmod_scalar"))(
    lambda data, scalar=0.0: jnp.mod(jnp.asarray(scalar, data.dtype), data))
register("rpower_scalar", num_inputs=1,
         aliases=("_rpower_scalar", "_npi_rpower_scalar"))(
    lambda data, scalar=0.0: jnp.power(jnp.asarray(scalar, data.dtype), data))


# ---------------------------------------------------------------------------
# where scalar variants (reference np_where_op.cc: scalar is x for lscalar,
# y for rscalar; scalar2 carries both as attrs x/y)
# ---------------------------------------------------------------------------

@register("where_lscalar", num_inputs=2, aliases=("_npi_where_lscalar",))
def where_lscalar(condition, y, scalar=0.0):
    return jnp.where(condition != 0, jnp.asarray(scalar, y.dtype), y)


@register("where_rscalar", num_inputs=2, aliases=("_npi_where_rscalar",))
def where_rscalar(condition, x, scalar=0.0):
    return jnp.where(condition != 0, x, jnp.asarray(scalar, x.dtype))


@register("where_scalar2", num_inputs=1, differentiable=False,
          aliases=("_npi_where_scalar2",))
def where_scalar2(condition, x=0.0, y=0.0):
    return jnp.where(condition != 0, jnp.float32(x), jnp.float32(y))


# ---------------------------------------------------------------------------
# indexing / assignment (reference np_indexing_op.cc, np_boolean_mask*.cc,
# np_index_add/update via _npx_)
# ---------------------------------------------------------------------------

@register("advanced_indexing", num_inputs=2, differentiable=False,
          aliases=("_npi_advanced_indexing",))
def advanced_indexing(data, indices):
    return data[jnp.asarray(indices).astype(jnp.int32)]


@register("advanced_indexing_multiple", num_inputs=-1, differentiable=False,
          aliases=("_npi_advanced_indexing_multiple",))
def advanced_indexing_multiple(arrays):
    data = arrays[0]
    idx = tuple(jnp.asarray(i).astype(jnp.int32) for i in arrays[1:])
    return data[idx]


@register("boolean_mask_assign_scalar", num_inputs=2, differentiable=False,
          aliases=("_npi_boolean_mask_assign_scalar",))
def boolean_mask_assign_scalar(data, mask, value=0.0):
    m = mask.astype(jnp.bool_)
    m = m.reshape(m.shape + (1,) * (data.ndim - m.ndim))
    return jnp.where(m, jnp.asarray(value, data.dtype), data)


@register("boolean_mask_assign_tensor", num_inputs=3, differentiable=False,
          aliases=("_npi_boolean_mask_assign_tensor",))
def boolean_mask_assign_tensor(data, mask, value):
    """data[mask] = value for a value broadcastable against ``data``; the
    reference's compressed (n_masked, ...) value layout is
    dynamic-shaped and handled on the host by the frontend."""
    m = mask.astype(jnp.bool_)
    m = m.reshape(m.shape + (1,) * (data.ndim - m.ndim))
    return jnp.where(m, jnp.broadcast_to(value.astype(data.dtype),
                                         data.shape), data)


@register("index_add", num_inputs=3, differentiable=False,
          aliases=("_npx_index_add",))
def index_add(data, indices, val):
    """data.at[ind].add(val) — ``indices`` is the reference's (k, n) stacked
    coordinate layout (np_index_add/update share it)."""
    idx = tuple(indices.astype(jnp.int32))
    return data.at[idx].add(val.astype(data.dtype))


@register("index_update", num_inputs=3, differentiable=False,
          aliases=("_npx_index_update",))
def index_update(data, indices, val):
    idx = tuple(indices.astype(jnp.int32))
    return data.at[idx].set(val.astype(data.dtype))


@register("constraint_check", differentiable=False,
          aliases=("_npx_constraint_check",))
def constraint_check(data, msg="Constraint violated!"):
    """All-true check gate (reference np_constraint_check.cc): returns a
    bool scalar; eager callers raise on False at the sync point."""
    return jnp.all(data != 0)


# ---------------------------------------------------------------------------
# straight-through / gradient-scaling contrib ops
# (reference contrib/stes_op.cc, contrib/gradient_multiplier_op.cc)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _round_ste(x):
    return jnp.round(x)


def _round_ste_fwd(x):
    return jnp.round(x), None


def _round_ste_bwd(_, g):
    return (g,)


_round_ste.defvjp(_round_ste_fwd, _round_ste_bwd)


@register("round_ste", aliases=("_contrib_round_ste",))
def round_ste(data):
    """Round with straight-through gradient (reference contrib/stes_op.cc)."""
    return _round_ste(data)


@jax.custom_vjp
def _sign_ste(x):
    return jnp.sign(x)


def _sign_ste_fwd(x):
    return jnp.sign(x), None


def _sign_ste_bwd(_, g):
    return (g,)


_sign_ste.defvjp(_sign_ste_fwd, _sign_ste_bwd)


@register("sign_ste", aliases=("_contrib_sign_ste",))
def sign_ste(data):
    return _sign_ste(data)


@register("gradientmultiplier", aliases=("_contrib_gradientmultiplier",))
def gradientmultiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by ``scalar`` (reference
    contrib/gradient_multiplier_op.cc — gradient-reversal layers)."""

    @jax.custom_vjp
    def _f(x):
        return x

    def _fwd(x):
        return x, None

    def _bwd(_, g):
        return (g * scalar,)

    _f.defvjp(_fwd, _bwd)
    return _f(data)


@register("square_sum", aliases=("_square_sum",))
def square_sum(data, axis=None, keepdims=False):
    """sum(x*x) fused (reference square_sum.cc, row-sparse-oriented)."""
    return jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims)


# ---------------------------------------------------------------------------
# legacy activation (reference softmax_activation.cc)
# ---------------------------------------------------------------------------

@register("SoftmaxActivation", aliases=("softmax_activation",))
def softmax_activation(data, mode="instance"):
    """mode='instance': softmax over the trailing flattened axes per batch
    row; mode='channel': softmax over axis 1."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


# ---------------------------------------------------------------------------
# distributions missing registered spellings
# (reference numpy/random/np_*_op.cc)
# ---------------------------------------------------------------------------

@register("laplace", num_inputs=0, differentiable=False,
          aliases=("_npi_laplace",), draws_key=True)
def laplace(loc=0.0, scale=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return loc + scale * jax.random.laplace(key, tuple(size), _dt(dtype))


@register("gumbel", num_inputs=0, differentiable=False,
          aliases=("_npi_gumbel",), draws_key=True)
def gumbel(loc=0.0, scale=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return loc + scale * jax.random.gumbel(key, tuple(size), _dt(dtype))


@register("logistic", num_inputs=0, differentiable=False,
          aliases=("_npi_logistic",), draws_key=True)
def logistic(loc=0.0, scale=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return loc + scale * jax.random.logistic(key, tuple(size), _dt(dtype))


@register("rayleigh", num_inputs=0, differentiable=False,
          aliases=("_npi_rayleigh",), draws_key=True)
def rayleigh(scale=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    u = jax.random.uniform(key, tuple(size), _dt(dtype), minval=1e-7,
                           maxval=1.0)
    return scale * jnp.sqrt(-2.0 * jnp.log(u))


@register("pareto", num_inputs=0, differentiable=False,
          aliases=("_npi_pareto",), draws_key=True)
def pareto(a=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    return jax.random.pareto(key, a, tuple(size), _dt(dtype)) - 1.0


@register("weibull", num_inputs=0, differentiable=False,
          aliases=("_npi_weibull",), draws_key=True)
def weibull(a=1.0, size=(1,), dtype=None, key=None):
    key = key if key is not None else _rng.next_key()
    u = jax.random.uniform(key, tuple(size), _dt(dtype), minval=1e-7,
                           maxval=1.0)
    return jnp.power(-jnp.log(u), 1.0 / a)


@register("powerd", num_inputs=0, differentiable=False,
          aliases=("_npi_powerd",), draws_key=True)
def powerd(a=1.0, size=(1,), dtype=None, key=None):
    """np.random.power: density a*x^(a-1) on [0, 1] — inverse-CDF
    transform u^(1/a)."""
    key = key if key is not None else _rng.next_key()
    u = jax.random.uniform(key, tuple(size), _dt(dtype), minval=1e-7,
                           maxval=1.0)
    return jnp.power(u, 1.0 / a)


@register("choice", num_inputs=0, differentiable=False,
          aliases=("_npi_choice",), draws_key=True)
def choice(a=1, size=(1,), replace=True, weights=None, key=None):
    key = key if key is not None else _rng.next_key()
    pool = jnp.arange(int(a)) if isinstance(a, (int, float)) else jnp.asarray(a)
    p = None if weights is None else jnp.asarray(weights)
    return jax.random.choice(key, pool, tuple(size), replace=replace, p=p)


@register("generalized_negative_binomial", num_inputs=0,
          differentiable=False,
          aliases=("_sample_generalized_negative_binomial",
                   "random_generalized_negative_binomial"), draws_key=True)
def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,), dtype=None,
                                  key=None):
    """Gamma-Poisson mixture with mean mu, dispersion alpha (reference
    random/sample_op.cc GeneralizedNegativeBinomialSampler)."""
    key = key if key is not None else _rng.next_key()
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, tuple(shape)) * (alpha * mu)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))
