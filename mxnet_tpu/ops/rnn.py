"""Fused multi-layer RNN/LSTM/GRU operator.

Reference analog: the stateful fused RNN op (``src/operator/rnn-inl.h``
1,608 LoC + ``rnn.cc:451`` — vanilla CPU impl and cuDNN wrapper).
TPU-native design (SURVEY.md §2.2 "rnn*": *implement as XLA scan lowering*):
one ``lax.scan`` per layer-direction over time-major data; XLA pipelines the
per-step matmuls onto the MXU and fuses the gate math.  Gate layouts match
cuDNN (LSTM: i f g o; GRU: r z n) so exported weights are interchangeable
with the reference's packed format.

Weights arrive as separate arrays per (layer, direction): no cuDNN packed
1-D parameter blob — packing was a cuDNN calling-convention artifact, not a
feature; :mod:`mxnet_tpu.gluon.rnn` keeps per-layer named Parameters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["rnn_fused"]


def _step_rnn_tanh(x_proj, h, w_hh, b_hh):
    return jnp.tanh(x_proj + h @ w_hh.T + b_hh)


def _step_rnn_relu(x_proj, h, w_hh, b_hh):
    return jax.nn.relu(x_proj + h @ w_hh.T + b_hh)


def _layer_scan(mode, x, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse=False):
    """Run one direction of one layer over time. x: (T, B, I)."""
    # hoist the input projection out of the scan: one big MXU matmul over
    # (T*B, I) instead of T small ones
    T, B, _ = x.shape
    x_proj = (x.reshape(T * B, -1) @ w_ih.T + b_ih).reshape(T, B, -1)
    if reverse:
        x_proj = jnp.flip(x_proj, axis=0)

    if mode == "lstm":
        def step(carry, xp):
            h, c = carry
            gates = xp + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        (hT, cT), ys = lax.scan(step, (h0, c0), x_proj)
    elif mode == "gru":
        def step(h, xp):
            xr, xz, xn = jnp.split(xp, 3, axis=-1)
            hr, hz, hn = jnp.split(h @ w_hh.T + b_hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h = (1.0 - z) * n + z * h
            return h, h

        hT, ys = lax.scan(step, h0, x_proj)
        cT = None
    else:
        fn = _step_rnn_tanh if mode == "rnn_tanh" else _step_rnn_relu

        def step(h, xp):
            h = fn(xp, h, w_hh, b_hh)
            return h, h

        hT, ys = lax.scan(step, h0, x_proj)
        cT = None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


@register("_rnn_fused", num_inputs=-1, num_outputs=-1)
def rnn_fused(arrays, mode="lstm", hidden_size=0, num_layers=1,
              bidirectional=False, dropout=0.0, has_cell_state=None):
    """arrays = [data(T,B,I), h0(L*D,B,H), (c0 if lstm),
    then per (layer, direction): w_ih, w_hh, b_ih, b_hh,
    (dropout PRNG key last, iff dropout > 0 — explicit so the op stays pure
    under whole-graph jit, same contract as ops/nn.py Dropout)].

    Returns (output(T,B,H*D), hT(L*D,B,H)[, cT]) — the fused op contract of
    the reference RNN op (rnn-inl.h state_outputs=True shape semantics).
    """
    ndir = 2 if bidirectional else 1
    is_lstm = mode == "lstm" if has_cell_state is None else has_cell_state
    data = arrays[0]
    h0 = arrays[1]
    idx = 2
    c0 = None
    if is_lstm:
        c0 = arrays[2]
        idx = 3
    weights = list(arrays[idx:])
    key = None
    if dropout > 0.0:
        key = weights.pop()
    assert len(weights) == 4 * num_layers * ndir, (
        f"expected {4 * num_layers * ndir} weight arrays, got {len(weights)}")

    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        ys_dirs = []
        for d in range(ndir):
            wi = layer * ndir + d
            w_ih, w_hh, b_ih, b_hh = weights[4 * wi:4 * wi + 4]
            ys, hT, cT = _layer_scan(
                mode, x, h0[wi], c0[wi] if c0 is not None else None,
                w_ih, w_hh, b_ih, b_hh, reverse=(d == 1))
            ys_dirs.append(ys)
            h_outs.append(hT)
            if cT is not None:
                c_outs.append(cT)
        x = ys_dirs[0] if ndir == 1 else jnp.concatenate(ys_dirs, axis=-1)
        if dropout > 0.0 and layer < num_layers - 1:
            layer_key = jax.random.fold_in(key, layer)
            keep = jax.random.bernoulli(layer_key, 1.0 - dropout, x.shape)
            x = jnp.where(keep, x / (1.0 - dropout), 0.0)

    hT = jnp.stack(h_outs)
    if is_lstm:
        return x, hT, jnp.stack(c_outs)
    return x, hT
