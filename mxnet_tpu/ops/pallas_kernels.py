"""Pallas TPU kernels for the hot ops.

Flash attention (forward + backward) as Pallas kernels: tiled onto the MXU
with online softmax so the S×S score matrix never materializes in HBM —
O(S) memory instead of O(S²), the enabler for long-context training.

Reference analog: the fused transformer attention matmuls
(``src/operator/contrib/transformer.cc:650-740``,
``interleaved_matmul_selfatt_qk/valatt``) — which still materialized the
full score matrix; this is the TPU-first replacement, not a translation.

Off-TPU the kernels run under the Pallas interpreter (slow but exact) so
the CPU test suite validates the same code path that runs on hardware.

TPU lowering constraints honored throughout (Mosaic requires the last two
block dims divisible by (8, 128) or equal to the array dims): softmax
stats (m/l/lse/delta) are carried as COLUMN vectors with a trailing unit
dim — block (block_q, 1) passes because 1 == the array's own last dim.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "matmul_bn_stats", "conv1x1_bn_stats",
           "conv1x1_bn_stats_train", "fused_blocks",
           "conv3x3_bn_stats", "conv3x3_bn_stats_train", "conv3x3_fits",
           "convkxk_bn_stats", "convkxk_bn_stats_train", "convkxk_fits",
           "matmul_stats", "matmul_epilogue", "conv1x1_bn_act_train",
           "int8_matmul", "int8_blocks"]

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward kernel: one q-block per grid step, online softmax over k-blocks
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, block_k,
                causal, block_q, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale        # (block_q, d)
    d = q.shape[-1]

    num_kb = seq_len // block_k
    if causal:
        # only k-blocks at or before this q-block participate
        num_kb_eff = (qi + 1) * block_q // block_k
    else:
        num_kb_eff = num_kb

    def body(ki, carry):
        acc, m_prev, l_prev = carry                     # stats: (block_q, 1)
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T                                     # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha + p @ v
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb_eff, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l)).astype(jnp.float32)   # (block_q, 1)


# ---------------------------------------------------------------------------
# backward kernels: dq over q-blocks; dk/dv over k-blocks
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, sm_scale, block_k, causal, block_q, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                    # (block_q, 1)
    delta = delta_ref[0]                                # (block_q, 1)
    d = q.shape[-1]
    num_kb_eff = ((qi + 1) * block_q // block_k) if causal \
        else seq_len // block_k

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = do @ v.T
        ds = p * (dp - delta) * sm_scale
        return dq + ds @ k

    dq = jax.lax.fori_loop(0, num_kb_eff, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, *, sm_scale, block_q, causal, block_k, seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                    # (block_k, d)
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]
    num_qb = seq_len // block_q
    start_qb = (ki * block_k) // block_q if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]     # (block_q, 1)
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = (q @ k.T) * sm_scale                        # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta) * sm_scale
        dk = dk + ds.T @ q
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------


def _pick_block(seq_len, preferred=128):
    b = min(preferred, seq_len)
    while seq_len % b != 0:
        b //= 2
    return max(b, 1)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
        block_q=block_q, seq_len=s)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


def _bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k):
    bh, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)                       # (bh, s, 1)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                          block_k=block_k, causal=causal, block_q=block_q,
                          seq_len=s),
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          block_q=block_q, causal=causal, block_k=block_k,
                          seq_len=s),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, sm_scale):
    bh, s, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)
    out, _ = _fwd(q, k, v, causal, sm_scale, bq, bk)
    return out


def _flash_fwd(q, k, v, causal, sm_scale):
    bh, s, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)
    out, lse = _fwd(q, k, v, causal, sm_scale, bq, bk)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, res, do):
    q, k, v, out, lse = res
    bh, s, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)
    dq, dk, dv = _bwd(q, k, v, out, lse, do, causal, sm_scale, bq, bk)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, sm_scale=None):
    """Tiled attention: softmax(q kᵀ · scale [+ causal mask]) v.

    q/k/v: (..., num_heads, seq, head_dim); leading dims are flattened into
    the kernel grid.  Differentiable (custom VJP with flash backward).
    """
    orig_shape = q.shape
    *lead, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bh = 1
    for x in lead:
        bh *= x
    q3, k3, v3 = (t.reshape(bh, s, d) for t in (q, k, v))
    out = _flash(q3, k3, v3, causal, sm_scale)
    return out.reshape(orig_shape)


# ---------------------------------------------------------------------------
# fused matmul + BN-stats epilogue (docs/PERF.md kernel roadmap item 3)
# ---------------------------------------------------------------------------
#
# y = act(x @ w [+ bias]); per-column sum(y) and sum(y*y) accumulated in
# the SAME kernel — the producing matmul's epilogue computes the batch-norm
# statistics, removing the separate stats pass (one fewer HBM read of the
# activation).  This is exactly the fusion XLA cannot express: a reduction
# folded into a dot's output tiles.  Covers FullyConnected and 1x1-conv
# (NHWC collapsed to (N*H*W, C)) producers, which carry roughly half of
# ResNet-50's FLOPs.
#
# Reference analog: conv+BN folding exists in the reference only for
# INFERENCE (MKLDNN subgraph fuser); training-time stats fusion has no
# reference counterpart — TPU-first design.
#
# TPU grid semantics: grid iterations execute sequentially per core
# ("arbitrary" dimension semantics), so accumulating the (1, N)-tiled
# stats outputs across m-tiles is race-free by construction.


def _mm_stats_kernel(x_ref, w_ref, o_ref, s_ref, ss_ref, *, relu, k_tiles,
                     block_k):
    # m is the INNER grid dim: the same (1, block_n) stats block is then
    # revisited on consecutive grid steps, which is the only pattern whose
    # VMEM contents Pallas guarantees to persist for read-modify-write
    mi = pl.program_id(1)

    def body(ki, acc):
        xk = x_ref[:, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        wk = w_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        return acc + xk @ wk

    acc = jax.lax.fori_loop(
        0, k_tiles, body,
        jnp.zeros((x_ref.shape[0], w_ref.shape[1]), jnp.float32))
    if relu:
        acc = jnp.maximum(acc, 0.0)
    o_ref[...] = acc.astype(o_ref.dtype)
    part = jnp.sum(acc, axis=0, keepdims=True)          # (1, N_block)
    part_sq = jnp.sum(acc * acc, axis=0, keepdims=True)

    @pl.when(mi == 0)
    def _init():
        s_ref[...] = part
        ss_ref[...] = part_sq

    @pl.when(mi != 0)
    def _accum():
        s_ref[...] += part
        ss_ref[...] += part_sq


def matmul_bn_stats(x, w, relu=False, block_m=256, block_n=256,
                    block_k=512):
    """``y = act(x @ w)`` plus per-column ``sum(y)``/``sum(y*y)`` in one
    kernel pass.  x: (M, K), w: (K, N) -> (y: (M, N), s: (N,), ss: (N,)),
    stats in fp32.  M/K/N must be divisible by the (clamped) block sizes.
    Wrap 1x1 convs by collapsing NHWC to (N*H*W, C)."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        (m, k, n), (block_m, block_k, block_n))
    grid = (n // block_n, m // block_m)       # m innermost (see kernel)
    kernel = functools.partial(_mm_stats_kernel, relu=relu,
                               k_tiles=k // block_k, block_k=block_k)
    y, s, ss = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda ni, mi: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda ni, mi: (0, ni)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda ni, mi: (mi, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, w)
    return y, s[0], ss[0]


def conv1x1_bn_stats(x, w, relu=False, **blocks):
    """1x1-conv producer + BN-stats epilogue: x (N,H,W,Cin) NHWC,
    w (Cout,1,1,Cin) OHWI -> (y (N,H,W,Cout), mean (Cout,), var (Cout,)).
    The mean/var are the batch statistics BatchNorm(training=True) needs —
    computed without re-reading y from HBM."""
    n, h, wd, cin = x.shape
    cout = w.shape[0]
    x2 = x.reshape(n * h * wd, cin)
    w2 = w.reshape(cout, cin).T                  # (Cin, Cout)
    y, s, ss = matmul_bn_stats(x2, w2, relu=relu, **blocks)
    cnt = jnp.float32(n * h * wd)
    mean = s / cnt
    var = jnp.maximum(ss / cnt - mean * mean, 0.0)
    return y.reshape(n, h, wd, cout), mean, var


# ---------------------------------------------------------------------------
# Differentiable fused conv1x1 + BN-stats: the model-path entry point.
#
# Round-4 left matmul_bn_stats standalone; this wires it into training.
# Forward runs the Pallas producer+stats kernel (one HBM pass over the
# conv output instead of conv-write + stats-read); backward is explicit
# XLA (dense MXU matmuls) because pallas_call has no transpose rule.
# Reference analog: train-mode BN fusion does not exist in the reference
# (src/operator/nn/batch_norm.cc computes stats in a separate pass) —
# TPU-first design, used by gluon BatchNorm when its input was produced
# by an eligible 1x1 Convolution (see gluon/nn/basic_layers.py).
# ---------------------------------------------------------------------------


def fused_blocks(m, k, n):
    """Pick Mosaic-legal block sizes for matmul_bn_stats, or None when the
    shape can't tile: block_m multiple of 8 (sublane), block_n multiple of
    128 or the whole dim (lane), block_k any divisor of k."""
    def pick(dim, target, quantum):
        if dim <= target:
            return dim
        b = (min(target, dim) // quantum) * quantum
        while b >= quantum and dim % b:
            b -= quantum
        return b if b >= quantum and dim % b == 0 else None

    bm = pick(m, 256, 8)
    bn = pick(n, 256, 128)
    bk = pick(k, 512, 128)
    if bm is None or bn is None or bk is None:
        return None
    if m % bm or n % bn or k % bk:
        return None
    return {"block_m": bm, "block_n": bn, "block_k": bk}


@jax.custom_vjp
def conv1x1_bn_stats_train(x, w):
    """Differentiable ``(z, mean, var)`` of a 1x1 NHWC conv with fused
    batch statistics.  x (N,H,W,Cin), w (Cout,1,1,Cin) OHWI.  Caller must
    pre-check :func:`fused_blocks` eligibility."""
    z, mean, var = _c1x1_fwd(x, w)
    return z, mean, var


def _c1x1_fwd(x, w):
    n, h, wd, cin = x.shape
    blocks = fused_blocks(n * h * wd, cin, w.shape[0])
    return conv1x1_bn_stats(x, w, relu=False, **blocks)


def _c1x1_fwd_vjp(x, w):
    z, mean, var = _c1x1_fwd(x, w)
    return (z, mean, var), (x, w, z, mean)


def _c1x1_bwd(res, cts):
    x, w, z, mean = res
    gz, gmean, gvar = cts
    n, h, wd, cin = x.shape
    cout = w.shape[0]
    m = n * h * wd
    # total cotangent into the conv output: the stats outputs fold back as
    #   d mean_j / d z_ij = 1/M,   d var_j / d z_ij = 2 (z_ij - mean_j) / M
    z32 = z.reshape(m, cout).astype(jnp.float32)
    g = (gz.reshape(m, cout).astype(jnp.float32)
         + gmean[None, :].astype(jnp.float32) / m
         + gvar[None, :].astype(jnp.float32) * 2.0 * (z32 - mean[None, :]) / m)
    g = g.astype(x.dtype)                         # MXU-friendly operand dtype
    x2 = x.reshape(m, cin)
    w2 = w.reshape(cout, cin)
    dx = jax.lax.dot(g, w2.astype(g.dtype),
                     preferred_element_type=jnp.float32)
    dw = jax.lax.dot(g.T, x2, preferred_element_type=jnp.float32)
    return (dx.reshape(x.shape).astype(x.dtype),
            dw.reshape(w.shape).astype(w.dtype))


conv1x1_bn_stats_train.defvjp(_c1x1_fwd_vjp, _c1x1_bwd)


# ---------------------------------------------------------------------------
# Fused conv/BN/ReLU EPILOGUE family (round 9, ROADMAP item 2).
#
# The round-5 lesson (docs/PERF.md): a pallas_call is an opaque custom
# call XLA cannot fuse INTO, so a kernel that leaves ANY of the epilogue
# outside (scale/shift/relu/residual-add) breaks the surrounding fusion
# and loses.  These kernels take the other branch of that fork: put the
# ENTIRE consumer chain of the dominant ResNet 1x1 convs in-register —
#
#   matmul_stats     x @ w reduced DIRECTLY to per-column (sum, sumsq):
#                    the conv output is never written to HBM at all
#                    (the batch-norm statistics pass at 0 activation
#                    bytes);
#   matmul_epilogue  x @ w recomputed with bias -> BN scale-shift ->
#                    residual-add -> ReLU applied in-register, writing
#                    only the FINAL activation.
#
# Training conv+BN+ReLU(+residual) = stats pass + epilogue pass: ONE
# HBM pass over the conv output (the final write) instead of three
# (conv write, stats read, normalize read+write), at 2x matmul FLOPs —
# the flash-attention recompute trade applied to the conv path.  The
# backward (conv1x1_bn_act_train's custom_vjp) recomputes z with one
# dense MXU matmul, exactly like flash recomputes attention scores.
# No reference analog; wired via ops/nn.py _fused_conv1x1_bn_act into
# the model-zoo BottleneckV1 behind MXNET_FUSED_EPILOGUE.
# ---------------------------------------------------------------------------


def _mm_statsonly_kernel(x_ref, w_ref, s_ref, ss_ref, *, k_tiles, block_k):
    # m innermost (same revisit pattern as _mm_stats_kernel): the (1, bn)
    # stats tiles accumulate race-free across sequential m steps
    mi = pl.program_id(1)

    def body(ki, acc):
        xk = x_ref[:, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        wk = w_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        return acc + xk @ wk

    acc = jax.lax.fori_loop(
        0, k_tiles, body,
        jnp.zeros((x_ref.shape[0], w_ref.shape[1]), jnp.float32))
    part = jnp.sum(acc, axis=0, keepdims=True)
    part_sq = jnp.sum(acc * acc, axis=0, keepdims=True)

    @pl.when(mi == 0)
    def _init():
        s_ref[...] = part
        ss_ref[...] = part_sq

    @pl.when(mi != 0)
    def _accum():
        s_ref[...] += part
        ss_ref[...] += part_sq


def matmul_stats(x, w, block_m=256, block_n=256, block_k=512):
    """Per-column ``(sum(x@w), sum((x@w)**2))`` in fp32 WITHOUT writing
    the product: x (M, K), w (K, N) -> (s (N,), ss (N,)).  The
    activation-free half of the fused-epilogue pair."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        (m, k, n), (block_m, block_k, block_n))
    grid = (n // block_n, m // block_m)        # m innermost (see kernel)
    kernel = functools.partial(_mm_statsonly_kernel,
                               k_tiles=k // block_k, block_k=block_k)
    s, ss = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda ni, mi: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda ni, mi: (0, ni)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, w)
    return s[0], ss[0]


def _mm_epilogue_kernel(x_ref, w_ref, sc_ref, bi_ref, r_ref, o_ref, *,
                        k_tiles, block_k, relu, has_res):
    def body(ki, acc):
        xk = x_ref[:, pl.ds(ki * block_k, block_k)].astype(jnp.float32)
        wk = w_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        return acc + xk @ wk

    acc = jax.lax.fori_loop(
        0, k_tiles, body,
        jnp.zeros((x_ref.shape[0], w_ref.shape[1]), jnp.float32))
    out = acc * sc_ref[...] + bi_ref[...]       # BN scale-shift, (1, bn)
    if has_res:
        out = out + r_ref[...].astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    o_ref[...] = out.astype(o_ref.dtype)


def matmul_epilogue(x, w, scale, shift, residual=None, relu=False,
                    block_m=256, block_n=256, block_k=512):
    """``act((x @ w) * scale + shift [+ residual])`` in ONE kernel pass:
    x (M, K), w (K, N), scale/shift per-column fp32 (N,), residual
    (M, N) in the output dtype.  The residual adds BEFORE the relu —
    the ResNet block order ``relu(bn(conv(h)) + shortcut)``.  A conv
    bias folds into ``shift`` host-side (it is per-column affine)."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        (m, k, n), (block_m, block_k, block_n))
    has_res = residual is not None
    r = residual if has_res else jnp.zeros((1, 1), x.dtype)
    r_spec = (pl.BlockSpec((block_m, block_n), lambda ni, mi: (mi, ni))
              if has_res else pl.BlockSpec((1, 1), lambda ni, mi: (0, 0)))
    kernel = functools.partial(_mm_epilogue_kernel, k_tiles=k // block_k,
                               block_k=block_k, relu=relu, has_res=has_res)
    return pl.pallas_call(
        kernel,
        grid=(n // block_n, m // block_m),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda ni, mi: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda ni, mi: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
            pl.BlockSpec((1, block_n), lambda ni, mi: (0, ni)),
            r_spec,
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda ni, mi: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=_interpret(),
    )(x, w, scale.astype(jnp.float32).reshape(1, n),
      shift.astype(jnp.float32).reshape(1, n), r)


@functools.lru_cache(maxsize=None)
def _c1x1_act_train_for(relu, has_res, eps, fix_gamma):
    """One custom_vjp core per static (relu, has_residual, eps,
    fix_gamma) — jax.custom_vjp cannot take non-array args positionally."""

    def _fwd_impl(x, w, gamma, beta, *rs):
        n, h, wd, cin = x.shape
        cout = w.shape[0]
        m = n * h * wd
        x2 = x.reshape(m, cin)
        w2 = w.reshape(cout, cin).T
        blocks = fused_blocks(m, cin, cout)
        s, ss = matmul_stats(x2, w2, **blocks)
        cnt = jnp.float32(m)
        mean = s / cnt
        var = jnp.maximum(ss / cnt - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + jnp.float32(eps))
        g = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
        sc = inv * g
        bi = beta.astype(jnp.float32) - mean * sc
        r2 = rs[0].reshape(m, cout) if has_res else None
        out = matmul_epilogue(x2, w2, sc, bi, residual=r2, relu=relu,
                              **blocks)
        return out.reshape(n, h, wd, cout), mean, var

    @jax.custom_vjp
    def f(x, w, gamma, beta, *rs):
        return _fwd_impl(x, w, gamma, beta, *rs)

    def fwd(x, w, gamma, beta, *rs):
        out, mean, var = _fwd_impl(x, w, gamma, beta, *rs)
        return (out, mean, var), (x, w, gamma, beta,
                                  rs[0] if has_res else None, mean, var)

    def bwd(res, cts):
        x, w, gamma, beta, r, mean, var = res
        gout, gmean, gvar = cts
        n, h, wd, cin = x.shape
        cout = w.shape[0]
        m = n * h * wd
        x2 = x.reshape(m, cin)
        w2 = w.reshape(cout, cin)
        # recompute z on the MXU (the flash-style trade: z never hit HBM
        # in forward; one dense matmul rebuilds it here)
        z = jax.lax.dot(x2, w2.T, preferred_element_type=jnp.float32)
        z = z.astype(jnp.float32)
        f32 = jnp.float32
        inv = jax.lax.rsqrt(var + f32(eps))
        g = jnp.ones_like(inv) if fix_gamma else gamma.astype(f32)
        sc = inv * g
        xhat = (z - mean[None, :]) * inv[None, :]
        y = sc[None, :] * z + (beta.astype(f32) - mean * sc)[None, :]
        ga = gout.reshape(m, cout).astype(f32)
        if has_res:
            a = y + r.reshape(m, cout).astype(f32)
        else:
            a = y
        if relu:
            ga = jnp.where(a > 0, ga, 0.0)
        # d residual: the add sits under the relu, so it shares ga
        dr = (ga.astype(r.dtype).reshape(r.shape) if has_res else None)
        dbeta_f = jnp.sum(ga, axis=0)
        dgamma_f = jnp.sum(ga * xhat, axis=0)
        # BN backward into z (mean/var chains folded), per column:
        #   dz = sc * (ga - mean_M(ga) - xhat * mean_M(ga * xhat))
        dz = sc[None, :] * (ga - dbeta_f[None, :] / m
                            - xhat * dgamma_f[None, :] / m)
        # plus the DIRECT cotangents on the returned stats outputs
        #   d mean_j / d z_ij = 1/M,  d var_j / d z_ij = 2 (z_ij - mu_j)/M
        dz = (dz + gmean[None, :].astype(f32) / m
              + gvar[None, :].astype(f32) * 2.0 * (z - mean[None, :]) / m)
        dz = dz.astype(x.dtype)                  # MXU-friendly operands
        dx = jax.lax.dot(dz, w2.astype(dz.dtype),
                         preferred_element_type=jnp.float32)
        dw = jax.lax.dot(dz.T, x2, preferred_element_type=jnp.float32)
        dgamma = (jnp.zeros_like(gamma) if fix_gamma
                  else dgamma_f.astype(gamma.dtype))
        dbeta = dbeta_f.astype(beta.dtype)
        outs = (dx.reshape(x.shape).astype(x.dtype),
                dw.reshape(w.shape).astype(w.dtype), dgamma, dbeta)
        return outs + ((dr,) if has_res else ())

    f.defvjp(fwd, bwd)
    return f


def conv1x1_bn_act_train(x, w, gamma, beta, residual=None, eps=1e-5,
                         relu=True, fix_gamma=False):
    """Differentiable fused 1x1-conv + train-mode BN + residual-add +
    ReLU: x (N,H,W,Cin) NHWC, w (Cout,1,1,Cin) OHWI, ``residual``
    (N,H,W,Cout) added before the relu -> ``(out, mean, var)``, stats
    fp32.  The conv output never materializes in HBM (stats pass +
    in-register epilogue pass); the backward recomputes it with one
    dense matmul.  Caller pre-checks :func:`fused_blocks`."""
    core = _c1x1_act_train_for(bool(relu), residual is not None,
                               float(eps), bool(fix_gamma))
    if residual is not None:
        return core(x, w, gamma, beta, residual)
    return core(x, w, gamma, beta)


# ---------------------------------------------------------------------------
# int8 matmul with s32 accumulation — the MEASUREMENT kernel (round 9).
#
# History: round 5 shipped whole-K-row int8 kernels (x block (bm, K)
# resident, fori over K slices) plus conv1x1/conv3x3 wrappers wired into
# contrib/quantization.py behind MXNET_INT8_PALLAS.  The chip bench
# measured that route at 0.345x of plain lax.conv s8 (BENCH_builder_r05
# pallas_vs_lax) with int8 itself losing to bf16 at matched batch — so
# round 9 DELETED the conv wrappers and the production routing (the knob
# now refuses, contrib/quantization.py), and rebuilt the matmul itself in
# the canonical Pallas shape so the microbench keeps an honest A/B
# vehicle: full (m, n, k) grid with k innermost, an s32 VMEM scratch
# accumulator revisited across k steps (VMEM footprint bm*bk + bk*bn +
# bm*bn instead of bm*K whole rows — the round-5 kernel's K-resident rows
# are what starved double-buffering), and the fp32 dequant / relu / s8
# requantize epilogue applied IN REGISTER on the last k step only.
# benchmark/microbench_tpu.py section_int8_pallas re-measures it against
# lax; production re-entry requires that bench to win on chip.
# ---------------------------------------------------------------------------


def _int8_mm_kernel(x_ref, w_ref, o_ref, acc_ref, *, k_tiles, scale, relu,
                    out_scale):
    ki = pl.program_id(2)                     # k innermost: the same
                                              # (m, n) tile is revisited
    @pl.when(ki == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(ki == k_tiles - 1)
    def _epilogue():
        out = acc_ref[...].astype(jnp.float32) * scale
        if relu:
            out = jnp.maximum(out, 0.0)
        if out_scale is not None:
            q = jnp.clip(jnp.round(out * out_scale), -127, 127)
            o_ref[...] = q.astype(jnp.int8)
        else:
            o_ref[...] = out.astype(o_ref.dtype)


def int8_blocks(m, k, n):
    """Mosaic-legal tiles for s8 operands: sublane quantum 32, lane 128
    (or whole-dimension blocks)."""
    def pick(dim, target, quantum):
        if dim <= target:
            return dim
        b = (min(target, dim) // quantum) * quantum
        while b >= quantum and dim % b:
            b -= quantum
        return b if b >= quantum and dim % b == 0 else None

    bm = pick(m, 256, 32)
    bn = pick(n, 256, 128)
    bk = pick(k, 512, 128)
    if bm is None or bn is None or bk is None:
        return None
    if m % bm or n % bn or k % bk:
        return None
    return {"block_m": bm, "block_n": bn, "block_k": bk}


def int8_matmul(x, w, scale, relu=False, out_scale=None,
                block_m=256, block_n=256, block_k=512):
    """``dequant(x_s8 @ w_s8)``: x (M, K) s8, w (K, N) s8 -> fp32 (M, N)
    scaled by ``scale`` (= data_scale * w_scale), with the optional relu
    and s8 requantize (``out_scale``: fp32 -> s8 multiplier) fused
    in-register on the final k step.  s32 accumulation in a VMEM scratch
    tile on the MXU int8 path; (m, n, k) grid, k innermost."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    block_m = min(block_m, m)
    block_n = min(block_n, n)
    block_k = min(block_k, k)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        (m, k, n), (block_m, block_k, block_n))
    k_tiles = k // block_k
    kernel = functools.partial(
        _int8_mm_kernel, k_tiles=k_tiles, scale=float(scale), relu=relu,
        out_scale=None if out_scale is None else float(out_scale))
    out_dtype = jnp.int8 if out_scale is not None else jnp.float32
    return pl.pallas_call(
        kernel,
        grid=(m // block_m, n // block_n, k_tiles),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_n), lambda mi, ni, ki: (ki, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.int32)],
        interpret=_interpret(),
    )(x, w)


# ---------------------------------------------------------------------------
# 3x3 conv + BN-stats epilogue (round-5 VERDICT #2 second half).
#
# ResNet-50's 16 bottleneck 3x3 convs (stride 1, pad 1) are the BN sites
# the 1x1 fusion can't reach.  Every ResNet geometry keeps a full padded
# image tile resident in VMEM (56x56x64 -> 430 KB ... 7x7x2048 -> 230 KB),
# so the kernel grids over (cout-tiles, batch), pads in VMEM, and
# accumulates the conv as 9 statically-shifted matmuls on the MXU, with
# the same race-free batch-accumulated sum/sumsq epilogue as
# matmul_bn_stats (batch is the inner, sequential grid dim).
# No reference analog (src/operator/nn/batch_norm.cc stats are a
# separate pass) — TPU-first fusion.
# ---------------------------------------------------------------------------


def _tap_accumulate(xp_ref, w_ref, kh, kw, ho, wo, acc_dtype, w_cast=None):
    """Sum of shifted-window matmuls over the kh*kw taps: xp_ref a
    (Hp,Wp,Cin) already-padded VMEM ref, w_ref a (kh*kw,Cin,bn)
    taps-leading ref -> (ho*wo, bn).

    A fori_loop over the kh row shifts, NOT a fully unrolled Python
    loop: Mosaic's scoped-VMEM stack allocator keeps each unrolled
    iteration's shifted window + accumulator live simultaneously
    (kh*kw copies — the round-5 on-chip compile OOM); the loop body
    reuses one row block.  The row shift is a dynamic REF load
    (``pl.ds`` on the untiled leading dim — this Pallas TPU lowering
    has no ``dynamic_slice`` on values, and Mosaic requires sublane-dim
    dynamic starts to be 8-aligned, so the kw column shifts stay as
    static slices unrolled inside the body)."""
    cin = xp_ref.shape[-1]
    bn = w_ref.shape[-1]

    def row(dy, acc):
        xr = xp_ref[pl.ds(dy, ho), :, :]            # (ho, Wp, cin)
        for dx in range(kw):
            xs = xr[:, dx:dx + wo, :].reshape(ho * wo, cin)
            wt = w_ref[pl.ds(dy * kw + dx, 1), :, :].reshape(cin, bn)
            if w_cast is not None:
                wt = wt.astype(w_cast)
            acc = acc + jax.lax.dot_general(
                xs, wt, (((1,), (0,)), ((), ())),
                preferred_element_type=acc_dtype)
        return acc

    return jax.lax.fori_loop(0, kh, row,
                             jnp.zeros((ho * wo, bn), acc_dtype))


def _ckxk_kernel(x_ref, w_ref, o_ref, s_ref, ss_ref, xp_ref, *, ho, wo,
                 kh, kw, ph, pw):
    bi = pl.program_id(1)
    x = x_ref[0].astype(jnp.float32)                  # (H, W, Cin)
    xp_ref[...] = (jnp.pad(x, ((ph, ph), (pw, pw), (0, 0)))
                   if (ph or pw) else x)
    bn = w_ref.shape[-1]
    acc = _tap_accumulate(xp_ref, w_ref, kh, kw, ho, wo, jnp.float32,
                          w_cast=jnp.float32)
    o_ref[0] = acc.reshape(ho, wo, bn).astype(o_ref.dtype)
    part = jnp.sum(acc, axis=0, keepdims=True)        # (1, bn)
    part_sq = jnp.sum(acc * acc, axis=0, keepdims=True)

    @pl.when(bi == 0)
    def _init():
        s_ref[...] = part
        ss_ref[...] = part_sq

    @pl.when(bi != 0)
    def _accum():
        s_ref[...] += part
        ss_ref[...] += part_sq


def convkxk_fits(xshape, cout, kernel=(3, 3), pad=(1, 1), block_n=128,
                 vmem_budget=12 * 2 ** 20 + 2 ** 19, itemsize=2):
    """Eligibility for the full-image-tile KxK stride-1 kernel: NHWC
    geometry whose tiles stay inside the VMEM budget, with a
    Mosaic-friendly cout tiling.  ``itemsize`` is the storage dtype's
    byte width (2 for bf16, 4 for fp32, 1 for the s8 kernel — which
    also switches the in-kernel buffer dtypes to what
    ``_c3x3_int8_kernel`` really allocates: s8 image/window/weights,
    s32 accumulator, fp32 output).

    The byte model counts buffers as Mosaic actually allocates them:
    the last dim padded to 128 lanes, the second-to-last to the dtype's
    sublane quantum (8 f32 / 16 bf16 / 32 s8).  Un-padded estimates
    under-count tiny-channel geometries ~10x — the s2d stem's cin=12
    pads to 128 lanes, which is how the round-5 on-chip compile blew the
    16 MB scoped-VMEM limit; with honest accounting the stem is simply
    ineligible and falls back to the unfused conv+BN pair."""
    n, h, w, cin = xshape
    kh, kw = kernel
    ph, pw = pad
    ho, wo = h + 2 * ph - kh + 1, w + 2 * pw - kw + 1
    if ho <= 0 or wo <= 0:
        return None
    bn = min(block_n, cout)
    if cout % bn or (bn % 128 and bn != cout):
        return None

    def up(v, q):
        return -(-v // q) * q

    def sub(isz):
        return {1: 32, 2: 16, 4: 8}.get(isz, 8)

    # per-buffer dtypes: the bf16/fp32 kernel pads+computes in fp32 and
    # stores the conv output in the input dtype; the s8 kernel keeps the
    # image/window/weights in s8, accumulates s32, and emits fp32.
    int8 = itemsize == 1
    img_isz = 1 if int8 else 4          # padded image + tap window
    w_isz = 1 if int8 else 4            # weight taps as computed with
    out_isz = 4 if int8 else itemsize   # output tile
    m = up(ho * wo, sub(img_isz))
    cl = up(cin, 128)
    bl = up(bn, 128)
    wp = w + 2 * pw
    vmem = (h * up(w, sub(itemsize)) * cl * itemsize  # input tile as loaded
            + (h + 2 * ph) * up(wp, sub(img_isz)) * cl * img_isz  # scratch
            + ho * up(wp, sub(img_isz)) * cl * img_isz  # row-shift block
            + 2 * m * cl * img_isz                  # live column windows
            + 2 * m * bl * 4                        # accumulator in/out
            + kh * kw * up(cin, sub(w_isz)) * bl * w_isz  # weight taps
            + ho * up(wo, sub(out_isz)) * bl * out_isz)   # output tile
    if vmem > vmem_budget:
        return None
    return {"block_n": bn, "out_hw": (ho, wo)}


def convkxk_bn_stats(x, w, pad=(1, 1), block_n=128):
    """x (N,H,W,Cin) NHWC, w (Cout,kh,kw,Cin) OHWI, stride 1, symmetric
    per-dim ``pad`` -> (z (N,Ho,Wo,Cout), mean, var), stats fp32."""
    n, h, wd, cin = x.shape
    cout, kh, kw, _ = w.shape
    fit = convkxk_fits(x.shape, cout, (kh, kw), pad, block_n,
                       itemsize=jnp.dtype(x.dtype).itemsize)
    assert fit is not None, (x.shape, w.shape, pad)
    bn = fit["block_n"]
    ho, wo = fit["out_hw"]
    grid = (cout // bn, n)                        # batch innermost
    kernel = functools.partial(_ckxk_kernel, ho=ho, wo=wo, kh=kh, kw=kw,
                               ph=pad[0], pw=pad[1])
    # taps-leading weight layout so the in-loop per-tap slice is on the
    # (cheap, untiled) leading dim
    wr = jnp.transpose(w, (1, 2, 3, 0)).reshape(kh * kw, cin, cout)
    z, s, ss = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h, wd, cin), lambda ci, b: (b, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cin, bn), lambda ci, b: (0, 0, ci)),
        ],
        out_specs=[
            pl.BlockSpec((1, ho, wo, bn), lambda ci, b: (b, 0, 0, ci)),
            pl.BlockSpec((1, bn), lambda ci, b: (0, ci)),
            pl.BlockSpec((1, bn), lambda ci, b: (0, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2 * pad[0], wd + 2 * pad[1], cin),
                       jnp.float32),
        ],
        interpret=_interpret(),
    )(x, wr)
    cnt = jnp.float32(n * ho * wo)
    mean = s[0] / cnt
    var = jnp.maximum(ss[0] / cnt - mean * mean, 0.0)
    return z, mean, var


def _ref_convkxk(x, w, pad):
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NHWC", "OHWI", "NHWC"))
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), [(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=dn)


@functools.lru_cache(maxsize=None)
def _ckxk_train_for(pad):
    """One custom_vjp core per static pad (jax.custom_vjp cannot take
    non-array args positionally)."""

    @jax.custom_vjp
    def f(x, w):
        return convkxk_bn_stats(x, w, pad)

    def fwd(x, w):
        z, mean, var = convkxk_bn_stats(x, w, pad)
        return (z, mean, var), (x, w, z, mean)

    def bwd(res, cts):
        x, w, z, mean = res
        gz, gmean, gvar = cts
        n, ho, wo, _ = z.shape
        m = n * ho * wo
        z32 = z.astype(jnp.float32)
        g = (gz.astype(jnp.float32)
             + gmean.astype(jnp.float32) / m
             + gvar.astype(jnp.float32) * 2.0 * (z32 - mean) / m)
        # conv input/weight grads through XLA's own transposed convs (MXU)
        _, vjp = jax.vjp(lambda x_, w_: _ref_convkxk(x_, w_, pad), x, w)
        dx, dw = vjp(g.astype(z.dtype))
        return dx.astype(x.dtype), dw.astype(w.dtype)

    f.defvjp(fwd, bwd)
    return f


def convkxk_bn_stats_train(x, w, pad=(1, 1)):
    """Differentiable (z, mean, var) of a stride-1 KxK NHWC conv with
    fused batch statistics.  Caller pre-checks :func:`convkxk_fits`."""
    return _ckxk_train_for((int(pad[0]), int(pad[1])))(x, w)


# 3x3 compatibility surface (the original round-5 entry points)
def conv3x3_fits(xshape, cout, block_n=128, vmem_budget=10 * 2 ** 20,
                 itemsize=2):
    return convkxk_fits(xshape, cout, (3, 3), (1, 1), block_n,
                        vmem_budget, itemsize)


def conv3x3_bn_stats(x, w, block_n=128):
    return convkxk_bn_stats(x, w, (1, 1), block_n)


def conv3x3_bn_stats_train(x, w):
    return convkxk_bn_stats_train(x, w, (1, 1))


def _ref_conv3x3(x, w):
    return _ref_convkxk(x, w, (1, 1))


# The round-5 int8 conv wrappers (int8_conv1x1 / int8_conv3x3 and the
# _c3x3_int8_kernel full-image-tile body) were DELETED in round 9: the
# chip bench measured the route at 0.345x of plain lax.conv s8
# (BENCH_builder_r05 pallas_vs_lax) and contrib/quantization.py now
# refuses MXNET_INT8_PALLAS with a pointer to that measurement.  The
# rebuilt int8_matmul above stays as the microbench's A/B vehicle.
