"""Control-flow operators: foreach / while_loop / cond.

Reference: ``src/operator/control_flow.cc`` (higher-order ops running
sub-Symbols through nested CachedOps).  TPU-native design: when executed
eagerly on NDArrays these run as Python loops (exactly what the reference's
imperative path did); inside a hybridized/jitted forward the same entry
points lower onto ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` so the
loop compiles into the XLA program — the compiler-friendly form the survey
calls for (SURVEY.md §2.2 control_flow row).
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["foreach", "while_loop", "cond", "scan_lowered"]


def _is_traced(x) -> bool:
    import jax.core as jcore

    return isinstance(x, jcore.Tracer)


def foreach(body: Callable, data, init_states):
    """``out, states = foreach(body, data, states)`` — body(step_data, states)
    -> (out, new_states).  Reference src/operator/control_flow.cc _foreach."""
    from ..ndarray.ndarray import NDArray

    single_data = not isinstance(data, (list, tuple))
    datas = [data] if single_data else list(data)
    single_state = not isinstance(init_states, (list, tuple))
    states = [init_states] if single_state else list(init_states)

    if isinstance(datas[0], NDArray):
        # eager python loop
        outputs = []
        for i in range(datas[0].shape[0]):
            step = [d[i] for d in datas]
            out, states = body(step[0] if single_data else step,
                               states[0] if single_state else states)
            if not isinstance(states, (list, tuple)):
                states = [states]
            else:
                states = list(states)
            outputs.append(out)
        from .. import nd as _nd_mod  # lazy

        if isinstance(outputs[0], (list, tuple)):
            stacked = [
                _stack_nd([o[k] for o in outputs]) for k in range(len(outputs[0]))
            ]
        else:
            stacked = _stack_nd(outputs)
        return stacked, (states[0] if single_state else states)

    # traced jax path -> lax.scan
    def scan_body(carry, xs):
        out, new_states = body(xs[0] if single_data else list(xs),
                               carry[0] if single_state else list(carry))
        if not isinstance(new_states, (list, tuple)):
            new_states = [new_states]
        return tuple(new_states), out

    carry, outs = jax.lax.scan(scan_body, tuple(states), tuple(datas))
    return outs, (carry[0] if single_state else list(carry))


def _stack_nd(arrs):
    from ..ndarray.ndarray import invoke

    return invoke("stack", list(arrs), {"axis": 0})


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int = None):
    """Reference _while_loop.  Eager: python while.  Traced: lax.while_loop
    (outputs-accumulation variant requires max_iterations, as the reference
    does)."""
    from ..ndarray.ndarray import NDArray

    single = not isinstance(loop_vars, (list, tuple))
    lvars = [loop_vars] if single else list(loop_vars)

    if isinstance(lvars[0], NDArray):
        outputs = []
        steps = 0
        while bool(cond_fn(*lvars)) and (
            max_iterations is None or steps < max_iterations
        ):
            out, lvars = func(*lvars)
            if not isinstance(lvars, (list, tuple)):
                lvars = [lvars]
            else:
                lvars = list(lvars)
            if out is not None:
                outputs.append(out)
            steps += 1
        stacked = _stack_nd(outputs) if outputs else None
        return stacked, (lvars[0] if single else lvars)

    def body(c):
        out, new = func(*c)
        if not isinstance(new, (list, tuple)):
            new = [new]
        return tuple(new)

    final = jax.lax.while_loop(lambda c: cond_fn(*c), body, tuple(lvars))
    return None, (final[0] if single else list(final))


def cond(pred, then_func: Callable, else_func: Callable, inputs=()):
    """Reference _cond."""
    from ..ndarray.ndarray import NDArray

    if isinstance(pred, NDArray) or isinstance(pred, (bool, int)):
        take_then = bool(pred) if not isinstance(pred, NDArray) else bool(pred.asscalar())
        return then_func(*inputs) if take_then else else_func(*inputs)
    return jax.lax.cond(pred, lambda args: then_func(*args),
                        lambda args: else_func(*args), tuple(inputs))


def scan_lowered(body, init_carry, xs, length=None):
    """Direct lax.scan exposure for traced code (RNN layers use this)."""
    return jax.lax.scan(body, init_carry, xs, length=length)
