"""Creation operators (reference ``src/operator/tensor/init_op.cc``)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("zeros", num_inputs=0, differentiable=False)
def zeros(shape=None, dtype="float32"):
    return jnp.zeros(shape, dtype=jnp.dtype(dtype) if isinstance(dtype, str) else dtype)


@register("ones", num_inputs=0, differentiable=False)
def ones(shape=None, dtype="float32"):
    return jnp.ones(shape, dtype=jnp.dtype(dtype) if isinstance(dtype, str) else dtype)


@register("full", num_inputs=0, differentiable=False)
def full(shape=None, value=0.0, dtype="float32"):
    return jnp.full(shape, value, dtype=jnp.dtype(dtype) if isinstance(dtype, str) else dtype)


@register("arange", num_inputs=0, differentiable=False)
def arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("linspace", num_inputs=0, differentiable=False)
def linspace(start=0, stop=1, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=jnp.dtype(dtype))


@register("eye", num_inputs=0, differentiable=False)
def eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=jnp.dtype(dtype))
