"""Additional nn operators: cross-device BatchNorm, fused BN+ReLU,
ROIPooling, and the im2col/col2im pair.

Reference:
- SyncBatchNorm: ``src/operator/contrib/sync_batch_norm-inl.h`` (cross-GPU
  mean/var via an engine-coordinated reduce).  TPU-native: when executed
  inside a ``shard_map``/``pmap`` with a bound mesh axis the statistics ride
  ``lax.pmean`` over ICI; eagerly (one chip holding the full batch) plain
  batch statistics are already "synchronized".
- BatchNormWithReLU: ``src/operator/contrib/batch_norm_relu.cc`` (fused
  BN+ReLU saving one memory pass; on TPU XLA fuses the relu anyway — the op
  exists for graph parity).
- ROIPooling: ``src/operator/roi_pooling.cc`` (max-pool over quantized ROI
  grid; predecessor of ROIAlign).
- im2col/col2im: ``src/operator/nn/im2col.cc`` — patch-matrix extraction so
  user code can express convolution as GEMM.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _bn_stats(x, axis_name=None):
    """Per-channel mean/var over (N, spatial), optionally pmean'd over a
    mesh axis (the SyncBatchNorm cross-device reduce)."""
    red = (0,) + tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red)
    mean_sq = jnp.mean(jnp.square(x), axis=red)
    if axis_name:
        mean = lax.pmean(mean, axis_name)
        mean_sq = lax.pmean(mean_sq, axis_name)
    var = mean_sq - jnp.square(mean)
    return mean, var


def _bn_apply(x, gamma, beta, mean, var, eps, fix_gamma):
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    inv = lax.rsqrt(var + eps).reshape(shape)
    return (x - mean.reshape(shape)) * inv * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("SyncBatchNorm", num_inputs=5, num_outputs=1,
          aliases=("_contrib_SyncBatchNorm",))
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key="", axis_name=None):
    """Cross-device BatchNorm.  ``axis_name`` names the mesh axis to
    synchronize statistics over when the op runs inside shard_map/pmap;
    ``ndev``/``key`` are accepted for reference-signature parity (the
    engine-side device group bookkeeping has no TPU analog — the mesh axis
    is the device group)."""
    if use_global_stats:
        return _bn_apply(data, gamma, beta, moving_mean, moving_var, eps,
                         fix_gamma)
    mean, var = _bn_stats(data, axis_name)
    return _bn_apply(data, gamma, beta, mean, var, eps, fix_gamma)


@register("BatchNormWithReLU", num_inputs=5, num_outputs=1,
          aliases=("_contrib_BatchNormWithReLU",))
def batch_norm_with_relu(data, gamma, beta, moving_mean, moving_var,
                         eps=1e-3, momentum=0.9, fix_gamma=True,
                         use_global_stats=False, axis=1):
    """Fused BatchNorm+ReLU (XLA fuses the two pointwise passes into the
    normalization anyway; registered for graph parity)."""
    if use_global_stats:
        out = _bn_apply(data, gamma, beta, moving_mean, moving_var, eps,
                        fix_gamma)
    else:
        mean, var = _bn_stats(data)
        out = _bn_apply(data, gamma, beta, mean, var, eps, fix_gamma)
    return jax.nn.relu(out)


@register("ROIPooling", num_inputs=2)
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max pooling over a quantized ROI grid (reference
    src/operator/roi_pooling.cc).  rois: (R, 5) of [batch_idx, x1, y1,
    x2, y2] in image coordinates."""
    ph, pw = pooled_size
    n, c, h, w = data.shape

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
        roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        img = data[batch_idx]  # (c, h, w)
        # dense grid evaluation: for each output bin take the max over the
        # pixels whose coordinates fall inside the (quantized) bin — static
        # shapes, so XLA can tile it (no per-bin dynamic slices)
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        ybin = jnp.floor((ys - y1) / bin_h)      # (h,)
        xbin = jnp.floor((xs - x1) / bin_w)      # (w,)
        yin = (ys >= y1) & (ys <= y2)
        xin = (xs >= x1) & (xs <= x2)
        y_onehot = (ybin[None, :] == jnp.arange(ph)[:, None]) & yin[None, :]
        x_onehot = (xbin[None, :] == jnp.arange(pw)[:, None]) & xin[None, :]
        # mask (ph, h) x (pw, w) -> (ph, pw, h, w) applied to img
        mask = y_onehot[:, None, :, None] & x_onehot[None, :, None, :]
        vals = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = vals.max(axis=(-1, -2))
        # empty bins (roi smaller than grid) -> 0, matching the reference
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


@register("im2col", num_inputs=1)
def im2col(data, kernel=(3, 3), stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Extract sliding patches into a column matrix (reference
    src/operator/nn/im2col.cc): (N, C, H, W) -> (N, C*kh*kw, L)."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    n, c, h, w = data.shape
    x = jnp.pad(data, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    out_h = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    out_w = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            y0, x0 = i * dh, j * dw
            sl = x[:, :, y0:y0 + sh * out_h:sh, x0:x0 + sw * out_w:sw]
            patches.append(sl.reshape(n, c, out_h * out_w))
    # (N, C, kh*kw, L) -> (N, C*kh*kw, L) with kernel fastest-varying per
    # channel, the reference layout
    col = jnp.stack(patches, axis=2)
    return col.reshape(n, c * kh * kw, out_h * out_w)


@register("col2im", num_inputs=1)
def col2im(col, output_size=(8, 8), kernel=(3, 3), stride=(1, 1),
           dilate=(1, 1), pad=(0, 0)):
    """Scatter-add columns back to the image (adjoint of im2col; reference
    src/operator/nn/im2col.cc col2im)."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    h, w = output_size
    n = col.shape[0]
    c = col.shape[1] // (kh * kw)
    out_h = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    out_w = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    x = jnp.zeros((n, c, h + 2 * ph, w + 2 * pw), col.dtype)
    patches = col.reshape(n, c, kh * kw, out_h, out_w)
    k = 0
    for i in range(kh):
        for j in range(kw):
            y0, x0 = i * dh, j * dw
            upd = patches[:, :, k]
            x = x.at[:, :, y0:y0 + sh * out_h:sh,
                     x0:x0 + sw * out_w:sw].add(upd)
            k += 1
    return x[:, :, ph:ph + h, pw:pw + w]
