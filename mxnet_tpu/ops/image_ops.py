"""Device-side image operators (reference ``src/operator/image/`` —
``_image_to_tensor``/``_image_normalize``/``_image_resize``/``_image_crop``
and random variants).  ``mxnet_tpu/image.py`` keeps the host-side
decode/augment pipeline; these run on-device inside graphs (e.g. a
normalize folded into the first conv by XLA).

Layout convention follows the reference: HWC (or NHWC) uint8/float in,
``to_tensor`` produces CHW float scaled to [0, 1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("to_tensor", num_inputs=1, aliases=("_image_to_tensor",))
def to_tensor(data):
    """HWC [0,255] -> CHW float32 [0,1] (reference image/totensor-inl.h);
    batched NHWC -> NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return x.transpose(2, 0, 1)
    return x.transpose(0, 3, 1, 2)


@register("image_normalize", num_inputs=1, aliases=("_image_normalize",))
def image_normalize(data, mean=(0.0,), std=(1.0,)):
    """Per-channel (x - mean) / std on CHW / NCHW float input (reference
    image/normalize_op-inl.h)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    m = jnp.asarray(mean, data.dtype).reshape(shape)
    s = jnp.asarray(std, data.dtype).reshape(shape)
    return (data - m) / s


def _resize_hwc(img, size_wh, interp):
    w, h = size_wh
    method = "linear" if interp == 1 else "nearest"
    return jax.image.resize(img, (h, w) + img.shape[2:], method=method)


@register("image_resize", num_inputs=1, aliases=("_image_resize",))
def image_resize(data, size=(0, 0), keep_ratio=False, interp=1):
    """Resize HWC/NHWC (reference image/resize-inl.h).  ``size``: (w, h)
    or a single int (shorter edge when keep_ratio, square otherwise)."""
    if isinstance(size, int):
        size = (size, size)
    w, h = int(size[0]), int(size[1])
    if data.ndim == 3:
        H, W = data.shape[:2]
    else:
        H, W = data.shape[1:3]
    if keep_ratio:
        # the reference only allows keep_ratio with a scalar size
        # (image/resize-inl.h); silently treating a (w, h) tuple as a
        # shorter-edge target would hand back an unexpected output shape
        if w != h:
            raise ValueError(
                "image_resize: keep_ratio=True requires a scalar size "
                f"(shorter-edge target), got (w, h) = ({w}, {h})")
        short = min(H, W)
        scale = w / short          # single-int semantics: shorter edge
        h, w = int(round(H * scale)), int(round(W * scale))
    method = "linear" if interp == 1 else "nearest"
    if data.ndim == 3:
        out_shape = (h, w, data.shape[2])
    else:
        out_shape = (data.shape[0], h, w, data.shape[3])
    return jax.image.resize(data.astype(jnp.float32), out_shape,
                            method=method).astype(data.dtype)


@register("image_crop", num_inputs=1, aliases=("_image_crop",))
def image_crop(data, x=0, y=0, width=1, height=1):
    """Fixed crop at (x, y) of size (width, height), HWC/NHWC (reference
    image/crop-inl.h)."""
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@register("image_random_crop", num_inputs=2, differentiable=False,
          aliases=("_image_random_crop",))
def image_random_crop(data, key, width=1, height=1):
    """Uniform-position crop; explicit PRNG key input (counter-based
    randomness — the functional analog of the reference's resource-pool
    RNG)."""
    jkey = key.reshape(-1)[:2].astype(jnp.uint32)   # raw threefry key
    if data.ndim == 3:
        H, W = data.shape[:2]
    else:
        H, W = data.shape[1:3]
    kx, ky = jax.random.split(jkey)
    x0 = jax.random.randint(kx, (), 0, max(W - width, 0) + 1)
    y0 = jax.random.randint(ky, (), 0, max(H - height, 0) + 1)
    if data.ndim == 3:
        return jax.lax.dynamic_slice(
            data, (y0, x0, 0), (height, width, data.shape[2]))
    return jax.lax.dynamic_slice(
        data, (0, y0, x0, 0),
        (data.shape[0], height, width, data.shape[3]))


@register("image_random_resized_crop", num_inputs=2, differentiable=False,
          aliases=("_image_random_resized_crop",))
def image_random_resized_crop(data, key, width=1, height=1,
                              area=(0.08, 1.0), ratio=(0.75, 1.333),
                              interp=1):
    """Random area/aspect crop then resize to (width, height) — the
    Inception-style augmentation (reference image/random_resized_crop)."""
    jkey = key.reshape(-1)[:2].astype(jnp.uint32)   # raw threefry key
    if data.ndim != 3:
        raise ValueError("image_random_resized_crop expects HWC input")
    H, W = data.shape[:2]
    ka, kr, kx, ky = jax.random.split(jkey, 4)
    target_area = jax.random.uniform(ka, (), minval=area[0],
                                     maxval=area[1]) * H * W
    aspect = jax.random.uniform(kr, (), minval=ratio[0], maxval=ratio[1])
    cw = jnp.clip(jnp.sqrt(target_area * aspect).astype(jnp.int32), 1, W)
    ch = jnp.clip(jnp.sqrt(target_area / aspect).astype(jnp.int32), 1, H)
    # traced bounds sample uniformly (a modulo fold would bias low offsets)
    x0 = jax.random.randint(kx, (), 0, jnp.maximum(W - cw + 1, 1))
    y0 = jax.random.randint(ky, (), 0, jnp.maximum(H - ch + 1, 1))
    # gather-based resize of the dynamic sub-window (static output shape):
    # fractional sample coordinates, bilinear when interp == 1
    fy = y0 + (jnp.arange(height) + 0.5) * ch / height - 0.5
    fx = x0 + (jnp.arange(width) + 0.5) * cw / width - 0.5
    if interp == 1:
        y0i = jnp.clip(jnp.floor(fy), 0, H - 1).astype(jnp.int32)
        x0i = jnp.clip(jnp.floor(fx), 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0i + 1, 0, H - 1)
        x1i = jnp.clip(x0i + 1, 0, W - 1)
        wy = (jnp.clip(fy, 0, H - 1) - y0i)[:, None, None]
        wx = (jnp.clip(fx, 0, W - 1) - x0i)[None, :, None]
        v00 = data[y0i[:, None], x0i[None, :], :]
        v01 = data[y0i[:, None], x1i[None, :], :]
        v10 = data[y1i[:, None], x0i[None, :], :]
        v11 = data[y1i[:, None], x1i[None, :], :]
        out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)
    else:
        ys = jnp.clip(jnp.round(fy), 0, H - 1).astype(jnp.int32)
        xs = jnp.clip(jnp.round(fx), 0, W - 1).astype(jnp.int32)
        out = data[ys[:, None], xs[None, :], :]
    return out.astype(data.dtype)
