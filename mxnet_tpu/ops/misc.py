"""Miscellaneous parity operators: AMP casts, shape-like helpers, storage
casts, split_v2, in-place-style assignment ops, multi-tensor zeroing,
histogram, sparse introspection, and the Hawkes-process likelihood.

Reference files are cited per op; implementations are fresh JAX lowerings.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import enable_x64 as _enable_x64
from .registry import register


# --------------------------------------------------------------------------
# AMP casts (reference src/operator/tensor/amp_cast.cc)
# --------------------------------------------------------------------------

@register("amp_cast", num_inputs=1)
def amp_cast(data, dtype="float32"):
    """Mixed-precision cast node (reference amp_cast.cc); inserted by AMP
    graph conversion, kept as an explicit op so exported graphs round-trip.
    """
    return data.astype(jnp.dtype(dtype))


@register("amp_multicast", num_inputs=-1, num_outputs=-1)
def amp_multicast(arrays, num_outputs=0, cast_narrow=False):
    """Cast a list of arrays to their common widest (or narrowest) float
    type (reference amp_cast.cc amp_multicast).  Non-float inputs are
    never a cast target and pass through unchanged."""
    order = [jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64]

    def rank(dt):
        for i, o in enumerate(order):
            if dt == o:
                return i
        return None

    float_dts = [dt for dt in (a.dtype for a in arrays)
                 if rank(dt) is not None]
    if not float_dts:
        return tuple(arrays)
    target = (min if cast_narrow else max)(float_dts, key=rank)
    return tuple(a.astype(target) if rank(a.dtype) is not None else a
                 for a in arrays)


# --------------------------------------------------------------------------
# shape-like helpers (reference src/operator/tensor/elemwise_unary_op.cc)
# --------------------------------------------------------------------------

@register("broadcast_like", num_inputs=2)
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    """Broadcast lhs to the shape of rhs (reference broadcast_like,
    src/operator/tensor/broadcast_reduce_op_value.cc)."""
    if lhs_axes is not None or rhs_axes is not None:
        shape = list(lhs.shape)
        l_axes = lhs_axes if lhs_axes is not None else tuple(range(len(shape)))
        r_axes = rhs_axes if rhs_axes is not None else tuple(range(len(shape)))
        for la, ra in zip(l_axes, r_axes):
            shape[la] = rhs.shape[ra]
        return jnp.broadcast_to(lhs, tuple(shape))
    # rank-extend like broadcast_to: size-1 dims of lhs follow rhs
    return jnp.broadcast_to(lhs, rhs.shape)


@register("reshape_like", num_inputs=2)
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape, optionally splicing a sub-range of axes
    (reference reshape_like, src/operator/tensor/elemwise_unary_op_basic.cc).
    """
    if lhs_begin is None and rhs_begin is None:
        return lhs.reshape(rhs.shape)
    lb = lhs_begin or 0
    le = lhs_end if lhs_end is not None else len(lhs.shape)
    rb = rhs_begin or 0
    re_ = rhs_end if rhs_end is not None else len(rhs.shape)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return lhs.reshape(new_shape)


@register("cast_storage", num_inputs=1, differentiable=False)
def cast_storage(data, stype="default"):
    """Storage-type cast node (reference
    src/operator/tensor/cast_storage.cc).  Dense layout is the only device
    storage on TPU; row_sparse/csr live at the NDArray layer
    (ndarray/sparse.py .tostype()), so the graph node is an identity — the
    frontend wrapper performs the container conversion."""
    return data


# --------------------------------------------------------------------------
# split_v2 (reference src/operator/tensor/matrix_op.cc _split_v2)
# --------------------------------------------------------------------------

@register("split_v2", num_inputs=1, num_outputs=-1, aliases=("_split_v2",))
def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    """Split by section count or explicit indices (reference _split_v2)."""
    if sections and sections > 0:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [p.squeeze(axis) for p in parts]
    return tuple(parts)


# --------------------------------------------------------------------------
# assignment-style ops (reference src/operator/tensor/matrix_op.cc
# _slice_assign, init_op.cc _scatter_set_nd) — functional on TPU: they
# return the updated array; the NDArray frontend writes it back.
# --------------------------------------------------------------------------

@register("slice_assign", num_inputs=2, aliases=("_slice_assign",))
def slice_assign(data, value, begin=(), end=(), step=()):
    """data[begin:end:step] = value (reference _slice_assign)."""
    idx = tuple(
        slice(b if b is not None else None,
              e if e is not None else None,
              (s if s not in (None, 0) else None))
        for b, e, s in zip(begin, end,
                           step or (None,) * len(begin)))
    return data.at[idx].set(value)


@register("slice_assign_scalar", num_inputs=1,
          aliases=("_slice_assign_scalar",))
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    idx = tuple(
        slice(b if b is not None else None,
              e if e is not None else None,
              (s if s not in (None, 0) else None))
        for b, e, s in zip(begin, end,
                           step or (None,) * len(begin)))
    return data.at[idx].set(scalar)


@register("scatter_set_nd", num_inputs=3, aliases=("_scatter_set_nd",),
          differentiable=False)
def scatter_set_nd(lhs, indices, rhs, shape=None):
    """Set lhs at gather_nd-style indices to rhs (reference
    _scatter_set_nd, src/operator/tensor/indexing_op.cc)."""
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("reset_arrays", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def reset_arrays(arrays, num_arrays=0):
    """Zero a list of arrays in one fused program (reference
    src/operator/contrib/reset_arrays.cc — gradient clearing between
    accumulation windows)."""
    return tuple(jnp.zeros_like(a) for a in arrays)


# --------------------------------------------------------------------------
# histogram (reference src/operator/tensor/histogram.cc)
# --------------------------------------------------------------------------

@register("histogram", num_inputs=-1, num_outputs=-1, differentiable=False,
          aliases=("_histogram",))
def histogram(arrays, bin_cnt=None, range=None):
    """np.histogram semantics: with one input + bin_cnt/range attrs, or
    (data, bins) inputs (reference _histogram)."""
    data = arrays[0]
    if len(arrays) > 1:
        cnt, edges = jnp.histogram(data, bins=arrays[1])
    else:
        lo, hi = range if range is not None else (float(data.min()),
                                                  float(data.max()))
        cnt, edges = jnp.histogram(data, bins=bin_cnt or 10,
                                   range=(lo, hi))
    return cnt, edges


# --------------------------------------------------------------------------
# sparse introspection (dense-layout analogs)
# --------------------------------------------------------------------------

@register("getnnz", num_inputs=1, differentiable=False,
          aliases=("_contrib_getnnz",))
def getnnz(data, axis=None):
    """Count stored (non-zero) values (reference _contrib_getnnz over CSR;
    dense layout here, so it counts non-zeros)."""
    nz = (data != 0)
    with _enable_x64(True):   # reference returns int64 counts
        if axis is None:
            return jnp.sum(nz).astype(jnp.int64)
        return jnp.sum(nz, axis=axis).astype(jnp.int64)


@register("dynamic_reshape", num_inputs=2, differentiable=False,
          aliases=("_contrib_dynamic_reshape",))
def dynamic_reshape(data, shape):
    """Reshape where the target comes from a tensor (reference
    _contrib_dynamic_reshape).  Eager-only: under jit the target shape
    must be static — hybridized graphs should use ``reshape``."""
    import numpy as onp

    target = [int(x) for x in onp.asarray(shape)]
    return data.reshape(target)


# --------------------------------------------------------------------------
# Hawkes process log-likelihood (reference
# src/operator/contrib/hawkes_ll.cc:33-96)
# --------------------------------------------------------------------------

@register("hawkesll", num_inputs=8, num_outputs=-1,
          aliases=("_contrib_hawkesll",))
def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Univariate (per-mark) Hawkes log likelihood over ragged
    left-aligned sequences.

    lambda_k(t) = lda_k + alpha_k * beta_k * s_k(t) with memory
    s_k(t) = sum_{t_i<t, y_i=k} exp(-beta_k (t-t_i)) (+ decayed initial
    ``state``).  Returns (loglik (N,), out_state (N,K)); the compensator
    uses the closed form  integral = lda_k*T + alpha_k*(count_k + s0_k -
    s_k(T)).
    """
    N, T = lags.shape
    K = lda.shape[1]
    marks = marks.astype(jnp.int32)

    def per_sample(lda_i, s0, lags_i, marks_i, vl, tmax):
        def step(carry, inp):
            s, t, ll = carry
            dt, m, j = inp
            valid = j < vl
            s_dec = s * jnp.exp(-beta * dt)
            lam = lda_i[m] + alpha[m] * beta[m] * s_dec[m]
            ll = ll + jnp.where(valid, jnp.log(lam), 0.0)
            # padded steps must not decay the memory either — the state is
            # only advanced while inside the valid prefix
            s_new = jnp.where(valid, s_dec + jax.nn.one_hot(m, K), s)
            t_new = t + jnp.where(valid, dt, 0.0)
            return (s_new, t_new, ll), None

        init = (s0, jnp.zeros((), lags_i.dtype), jnp.zeros((), lags_i.dtype))
        (s_end, t_end, ll), _ = lax.scan(
            step, init,
            (lags_i, marks_i, jnp.arange(T)))
        # decay the memory to the end of the observation window
        s_T = s_end * jnp.exp(-beta * (tmax - t_end))
        counts = jnp.zeros(K).at[marks_i].add(
            (jnp.arange(T) < vl).astype(lags_i.dtype))
        comp = jnp.sum(lda_i * tmax + alpha * (counts + s0 - s_T))
        return ll - comp, s_T

    return jax.vmap(per_sample)(lda, state, lags, marks, valid_length,
                                max_time)


# --------------------------------------------------------------------------
# Custom op dispatch (reference src/operator/custom/custom-inl.h — Python
# callback op; here user ops register through mxnet_tpu.library.register_op
# and Custom dispatches to them by op_type for signature parity)
# --------------------------------------------------------------------------

@register("Custom", num_inputs=-1, num_outputs=-1)
def custom(arrays, op_type="", **attrs):
    """Dispatch by op_type (reference custom.cc): resolves ops registered
    via mx.operator.register (legacy CustomOpProp API) or
    library.register_op; extra attrs flow through to the target."""
    from .registry import find_op

    # legacy CustomOpProp registrations take PRIORITY over same-named
    # builtins (the reference keeps custom ops in their own registry)
    from .. import operator as _custom_operator

    prop_cls = _custom_operator.get_all_registered().get(op_type)
    if prop_cls is not None:
        return _custom_operator._invoke(prop_cls, list(arrays), attrs)
    schema = find_op(op_type)
    if schema is None:
        raise KeyError(
            f"Custom: no op '{op_type}' registered; register it with "
            "mx.operator.register (CustomOpProp API) or "
            "mxnet_tpu.library.register_op")
    if schema.num_inputs == -1:
        return schema.fn(list(arrays), **attrs)
    return schema.fn(*arrays, **attrs)


# --------------------------------------------------------------------------
# identity-with-attributes ops (reference src/operator/tensor/
# elemwise_unary_op_basic.cc, src/operator/regression_output.cc)
# --------------------------------------------------------------------------

@register("identity_with_attr_like_rhs", num_inputs=2,
          aliases=("_identity_with_attr_like_rhs",))
def identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs, shape/stype attributes taken from rhs (reference
    _identity_with_attr_like_rhs — used by the gradient of ops that drop
    storage attributes)."""
    return lhs


@register("IdentityAttachKLSparseReg", num_inputs=1)
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; attaches a KL-sparseness regularizer to the
    gradient in the reference (src/operator/identity_attach_KL_sparse_reg.cc).
    The regularization gradient is data-independent bookkeeping the
    reference applies in backward; forward parity is identity."""
    return data
