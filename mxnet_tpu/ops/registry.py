"""The operator registry.

Each operator is registered once, by name, with:

- ``fn``: a *pure JAX function* ``fn(*arrays, **attrs) -> array | tuple``.
  Array arguments are jax.Arrays; attrs are static python values.  Because
  ops are pure jax, the same registry serves the imperative path (eager
  dispatch, XLA-compiled per shape/dtype by jax's op-by-op cache), the
  hybridized path (whole-graph ``jax.jit``), and the symbolic path
  (Symbol graphs re-execute the same fns under tracing).
- ``num_inputs``: number of leading array args (-1 = variadic; the variadic
  arrays are passed as a single list argument).
- ``differentiable``: whether to build a VJP node on the autograd tape.

Reference analog: ``NNVM_REGISTER_OP`` attrs FCompute/FGradient/FInferShape
(``include/mxnet/op_attr_types.h:125-332``).  Shape/dtype inference comes for
free from jax's abstract evaluation (``jax.eval_shape``) instead of
hand-written FInferShape passes (``src/imperative/infer_graph_attr_pass.cc``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["OpSchema", "register", "get_op", "find_op", "list_ops", "alias"]


@dataclass
class OpSchema:
    name: str
    fn: Callable
    num_inputs: int = 1  # -1 => variadic (first arg is a list of arrays)
    num_outputs: int = 1  # -1 => variable, fn returns tuple
    differentiable: bool = True
    aliases: List[str] = field(default_factory=list)
    # namespaces this op is exported to ('nd', 'np', 'npx', 'internal')
    namespaces: List[str] = field(default_factory=lambda: ["nd"])
    doc: Optional[str] = None
    # last array input is a PRNG key the frontends auto-supply when the
    # caller omits it (the reference draws from the engine RNG at dispatch)
    rng_input: bool = False
    # op fn accepts a `key=` ATTR and draws from the global chain when it
    # is omitted — such a call must never be traced into a cached
    # executable (the draw would leak a tracer into the chain and bake
    # the key as a constant).  Declared explicitly per op: a signature
    # heuristic cannot tell a PRNG key from e.g. _index's indexing key,
    # and rng_input ops receive their key as an array input instead.
    draws_key: bool = False

    def __post_init__(self):
        if self.doc is None:
            self.doc = self.fn.__doc__


_OPS: Dict[str, OpSchema] = {}


def register(
    name: str,
    num_inputs: int = 1,
    num_outputs: int = 1,
    differentiable: bool = True,
    aliases: Sequence[str] = (),
    namespaces: Sequence[str] = ("nd",),
    rng_input: bool = False,
    draws_key: bool = False,
):
    """Decorator: register a pure-JAX function as an operator."""

    def deco(fn: Callable) -> Callable:
        schema = OpSchema(
            name=name,
            fn=fn,
            num_inputs=num_inputs,
            num_outputs=num_outputs,
            differentiable=differentiable,
            aliases=list(aliases),
            namespaces=list(namespaces),
            rng_input=rng_input,
            draws_key=draws_key,
        )
        if name in _OPS:
            raise ValueError(f"operator '{name}' registered twice")
        _OPS[name] = schema
        for a in schema.aliases:
            if a in _OPS:
                raise ValueError(f"operator alias '{a}' registered twice")
            _OPS[a] = schema
        return fn

    return deco


def alias(existing: str, *names: str):
    schema = get_op(existing)
    for n in names:
        if n in _OPS:
            raise ValueError(f"operator alias '{n}' registered twice")
        _OPS[n] = schema
        schema.aliases.append(n)


def get_op(name: str) -> OpSchema:
    if name not in _OPS:
        raise KeyError(f"operator '{name}' not registered")
    return _OPS[name]


def find_op(name: str) -> Optional[OpSchema]:
    return _OPS.get(name)


def list_ops(namespace: Optional[str] = None) -> List[str]:
    if namespace is None:
        return sorted(set(s.name for s in _OPS.values()))
    return sorted(set(s.name for s in _OPS.values() if namespace in s.namespaces))
