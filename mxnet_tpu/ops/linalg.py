"""Linear-algebra operators (reference ``src/operator/tensor/la_op.cc`` +
``src/operator/numpy/linalg/``).  XLA provides native lowerings for all of
these (cholesky/qr/svd/triangular_solve run on-device)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("linalg_gemm", num_inputs=3)
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2", num_inputs=2)
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_potri")
def linalg_potri(A):
    # inverse from cholesky factor
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_l = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@register("linalg_trsm", num_inputs=2)
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    if rightside:
        out = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(A, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(out, -1, -2)
    return jax.scipy.linalg.solve_triangular(
        A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("linalg_trmm", num_inputs=2)
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (jnp.matmul(B, a) if rightside else jnp.matmul(a, B))


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(A, offset=0):
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(A)
    return out.at[..., idx - offset, idx].set(A)


@register("linalg_inverse", aliases=["inverse"])
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det", aliases=["det"])
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", num_outputs=-1, aliases=["slogdet"])
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return (sign, logdet)


@register("linalg_svd", num_outputs=-1, aliases=["gesvd"])
def linalg_svd(A):
    u, s, vh = jnp.linalg.svd(A, full_matrices=False)
    return (u, s, vh)


@register("linalg_gelqf", num_outputs=-1)
def linalg_gelqf(A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return (jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2))


def _tri_n_from_packed(length: int, offset: int, lower: bool) -> int:
    """Solve n for len = tri(n, offset, lower) (reference la_op maketrian
    shape inference).  upper with offset k is the mirror of lower with
    offset -k, so normalize to the lower convention first."""
    eff = offset if lower else -offset
    k = abs(eff)
    # packed length of an n x n LOWER triangle with diagonal shifted:
    # eff<=0: (n-k)(n-k+1)/2 ; eff>0: n(n+1)/2 + k*n - k(k+1)/2
    for n in range(1, 4096):
        if eff <= 0:
            m = n - k
            if m >= 0 and m * (m + 1) // 2 == length:
                return n
        else:
            if n * (n + 1) // 2 + k * n - k * (k + 1) // 2 == length:
                return n
    raise ValueError(f"no triangle size matches packed length {length}")


@register("linalg_maketrian", aliases=["_linalg_maketrian"])
def linalg_maketrian(A, offset=0, lower=True):
    """Unpack a packed-triangle vector into a triangular matrix (reference
    src/operator/tensor/la_op.cc maketrian — inverse of extracttrian)."""
    length = A.shape[-1]
    n = _tri_n_from_packed(length, offset, lower)
    if lower:
        rows, cols = jnp.tril_indices(n, k=offset)
    else:
        rows, cols = jnp.triu_indices(n, k=offset)
    batch = A.shape[:-1]
    flat = A.reshape((-1, length))
    out = jnp.zeros((flat.shape[0], n, n), A.dtype)
    out = out.at[:, rows, cols].set(flat)
    return out.reshape(batch + (n, n))


@register("linalg_extracttrian", aliases=["_linalg_extracttrian"])
def linalg_extracttrian(A, offset=0, lower=True):
    """Pack a matrix triangle into a vector (reference la_op.cc
    extracttrian)."""
    n = A.shape[-1]
    if lower:
        rows, cols = jnp.tril_indices(n, k=offset)
    else:
        rows, cols = jnp.triu_indices(n, k=offset)
    batch = A.shape[:-2]
    flat = A.reshape((-1, n, n))
    out = flat[:, rows, cols]
    return out.reshape(batch + (out.shape[-1],))


@register("linalg_solve", num_inputs=2, aliases=["solve"])
def linalg_solve(A, B):
    return jnp.linalg.solve(A, B)


@register("linalg_tensorinv", aliases=["tensorinv"])
def linalg_tensorinv(A, ind=2):
    return jnp.linalg.tensorinv(A, ind=ind)


@register("linalg_cholesky", aliases=["cholesky"])
def linalg_cholesky(A, lower=True):
    L = jnp.linalg.cholesky(A)
    return L if lower else jnp.swapaxes(L, -1, -2)


@register("linalg_qr", num_outputs=-1, aliases=["qr"])
def linalg_qr(A):
    q, r = jnp.linalg.qr(A)
    return (q, r)


@register("linalg_eigh", num_outputs=-1, aliases=["eigh"])
def linalg_eigh(A, UPLO="L"):
    w, v = jnp.linalg.eigh(A, symmetrize_input=True)
    return (w, v)


@register("linalg_eigvalsh", aliases=["eigvalsh"])
def linalg_eigvalsh(A, UPLO="L"):
    return jnp.linalg.eigvalsh(A)


@register("linalg_syevd", num_outputs=-1, aliases=["_linalg_syevd"])
def linalg_syevd(A):
    """Symmetric eigendecomposition with the REFERENCE's syevd contract
    (src/operator/tensor/la_op.cc syevd): returns (U, L) where the ROWS of
    U are the eigenvectors, so A = U^T @ diag(L) @ U — note the reversed
    output order and transposed layout vs jnp.linalg.eigh's (w, v)."""
    w, v = jnp.linalg.eigh(A, symmetrize_input=True)
    return (jnp.swapaxes(v, -1, -2), w)


@register("linalg_norm_np", aliases=["np_norm"])
def linalg_norm_np(x, ord=None, axis=None, keepdims=False):
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims)


@register("linalg_matrix_rank", aliases=["matrix_rank"], differentiable=False)
def linalg_matrix_rank(M, tol=None):
    return jnp.linalg.matrix_rank(M, tol)


@register("linalg_pinv", aliases=["pinv"])
def linalg_pinv(a, rcond=1e-15):
    return jnp.linalg.pinv(a, rcond)


@register("linalg_lstsq", num_inputs=2, num_outputs=-1, aliases=["lstsq"])
def linalg_lstsq(a, b, rcond="warn"):
    rc = None if rcond == "warn" else rcond
    x, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rc)
    return (x, res, rank, sv)
