"""Operator library: name -> JAX lowering registry.

TPU-native replacement for the reference's nnvm operator registry
(``NNVM_REGISTER_OP`` + FCompute kernels, ``include/mxnet/op_attr_types.h``).
Instead of per-device kernels, each op is a pure JAX function; XLA owns
fusion, tiling and memory planning (what the reference did with
MXPlanMemory / pointwise_fusion_pass / CSE in src/imperative and src/nnvm).
"""
from .registry import OpSchema, register, get_op, find_op, list_ops

from . import tensor  # noqa: F401  (registers ops on import)
from . import elemwise  # noqa: F401
from . import nn  # noqa: F401
from . import reduce as _reduce  # noqa: F401
from . import random as _random  # noqa: F401
from . import init as _init  # noqa: F401
from . import optimizer as _optimizer  # noqa: F401
from . import linalg as _linalg  # noqa: F401
from . import contrib as _contrib  # noqa: F401
from . import detection as _detection  # noqa: F401
from . import extra as _extra  # noqa: F401
from . import control_flow as _control_flow  # noqa: F401
from . import rnn as _rnn  # noqa: F401
from . import nn_extra as _nn_extra  # noqa: F401
from . import misc as _misc  # noqa: F401
from . import image_ops as _image_ops  # noqa: F401
from . import np_extra as _np_extra  # noqa: F401
from . import graph_sampling as _graph_sampling  # noqa: F401
from . import ref_aliases as _ref_aliases  # noqa: F401  (must be last;
# contrib.quantization registers late — mxnet_tpu/__init__ re-applies)

__all__ = ["OpSchema", "register", "get_op", "find_op", "list_ops"]
