"""Tensor manipulation operators.

Reference analog: ``src/operator/tensor/matrix_op.cc`` (reshape/transpose/
slice/concat/take/...), ``indexing_op.cc``, ``cast_storage`` etc.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as onp

from .. import base as _base
from ..base import S64_DEMOTING_PLATFORMS, bounded_cache_put, pow2_col_factor
from ..base import int32_overflow_dim as _concrete_big
from .registry import register


@register("reshape", aliases=["Reshape"])
def reshape(data, shape=None, reverse=False):
    # Support MXNet's special codes 0 (copy dim) and -1 (infer)
    shape = tuple(shape)
    if 0 in shape or -2 in shape or -3 in shape or -4 in shape:
        shape = _expand_reshape_codes(tuple(data.shape), shape)
    return jnp.reshape(data, shape)


@register("npx_reshape", aliases=["_npx_reshape"])
def npx_reshape(data, newshape=None, reverse=False, order="C"):
    """npx.reshape — the NUMPY-EXTENSION special codes (reference
    _numpy_op_doc.py:563): -1 infer, -2 copy dim, -3 drop a size-1 dim,
    -4 copy ALL remaining dims, -5 merge two consecutive dims, -6 split
    a dim into the two factors that follow."""
    src = tuple(data.shape)
    shape = list(newshape if isinstance(newshape, (list, tuple))
                 else [newshape])
    if reverse:
        # right-to-left SHAPE resolution only (data stays C-order): expand
        # the mirrored spec against the mirrored src, mirror the result
        out_rev = _expand_npx_codes(src[::-1], _reverse_npx_spec(shape),
                                    mirror_splits=True)
        return jnp.reshape(data, tuple(out_rev)[::-1])
    return jnp.reshape(data, tuple(_expand_npx_codes(src, shape)))


def _expand_npx_codes(src, shape, mirror_splits=False):
    out = []
    i = 0
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == -2:
            out.append(src[i]); i += 1
        elif s == -3:
            if src[i] != 1:
                raise ValueError(
                    f"npx.reshape -3 requires a size-1 dim, got {src[i]}")
            i += 1
        elif s == -4:
            out.extend(src[i:]); i = len(src)
        elif s == -5:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -6:
            d1, d2 = shape[j + 1], shape[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            if d1 * d2 != src[i]:
                raise ValueError(
                    f"npx.reshape -6: {d1}x{d2} != {src[i]}")
            out.extend([d2, d1] if mirror_splits else [d1, d2])
            i += 1; j += 2
        elif s == -1:
            out.append(-1); i += 1
        else:
            out.append(s); i += 1
        j += 1
    return out


def _reverse_npx_spec(shape):
    """Reverse an npx-reshape spec keeping -6's factor pairs attached."""
    groups = []
    j = 0
    while j < len(shape):
        if shape[j] == -6:
            groups.append(shape[j:j + 3])
            j += 3
        else:
            groups.append([shape[j]])
            j += 1
    return [v for g in reversed(groups) for v in g]


def _expand_reshape_codes(src, shape):
    """Implements MXNet reshape special codes 0/-1/-2/-3/-4
    (reference matrix_op.cc InferReshapeShape)."""
    out = []
    i = 0  # index into src
    j = 0
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = shape[j + 1], shape[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    return tuple(out)


@register("transpose")
def transpose(data, axes=None):
    return jnp.transpose(data, axes)


@register("swapaxes", aliases=["SwapAxis"])
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("flatten", aliases=["Flatten"])
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis)


@register("broadcast_to")
def broadcast_to(data, shape=None):
    shape = tuple(shape)
    if 0 in shape:  # 0 = keep the matching input dim, right-aligned
        offset = len(shape) - data.ndim
        shape = tuple(
            s if s != 0 else data.shape[i - offset]
            for i, s in enumerate(shape))
    return jnp.broadcast_to(data, shape)


@register("broadcast_axis", aliases=["broadcast_axes"])
def broadcast_axis(data, axis=None, size=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axes, sizes):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


@register("tile")
def tile(data, reps=None):
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis)


@register("pad", aliases=["Pad"])
def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    pw = list(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pairs, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pairs, mode=jmode)


@register("concat", num_inputs=-1, aliases=["Concat"])
def concat(arrays, dim=1):
    return jnp.concatenate(arrays, axis=dim)


@register("stack", num_inputs=-1)
def stack(arrays, axis=0):
    return jnp.stack(arrays, axis=axis)


@register("split", num_outputs=-1, aliases=["SliceChannel"])
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", aliases=["crop"])
def slice_op(data, begin=None, end=None, step=None):
    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step or []) + [None] * (ndim - len(step or []))
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", num_inputs=2)
def slice_like(data, shape_like, axes=None):
    tgt = shape_like.shape
    idx = [slice(None)] * data.ndim
    axes = axes if axes else range(data.ndim)
    for a in axes:
        idx[a] = slice(0, tgt[a])
    return data[tuple(idx)]


@register("take", num_inputs=2)
def take(a, indices, axis=0, mode="clip"):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    dim = a.shape[axis] if a.ndim else 0
    if _concrete_big(dim) and not _base.s64_demoting_backend():
        # x64-native backend (cpu): s64 gathers execute natively — invoke
        # dispatches s64-typed big-dim calls under enable_x64 — so plain
        # jnp.take is exact at any offset and works traced (autograd,
        # hybridize).  The int32 factorization below and its refusals are
        # TPU-runtime constraints only (ADVICE r5).
        return jnp.take(a, indices.astype(jnp.int64), axis=axis, mode=jmode)
    if _concrete_big(dim):
        # >int32-range gather: the TPU compiler rejects s64 dynamic
        # indexing outright ("X64 rewrite ... indices exceed 32-bits"),
        # so factorize each flat index into a (row, col) int32 pair over
        # a (dim/C, C) view — per-dim extents and indices then all fit
        # int32, which the hardware gathers natively.  The s64 index
        # arithmetic runs ON HOST (the AOT compiler demotes device s64
        # types, mismatching jax's s64 buffers).
        if a.ndim != 1:
            raise NotImplementedError(
                "take along a >int32-range dim of a multi-dim array is "
                "not supported (an int32 cast would silently wrap the "
                "indices); flatten to 1-D for the exact factorized "
                "gather, or reshape so every dim fits int32")
        if isinstance(indices, jax.core.Tracer):
            raise NotImplementedError(
                "take with non-concrete indices on a >int32-range dim "
                "(inside jit/hybridize traces, or under autograd.record, "
                "which traces the op for its vjp): the TPU compiler "
                "demotes s64 index types, so the exact factorization "
                "needs concrete index values.  Gather outside record()/"
                "hybridize, or reshape to a 2-D view whose dims fit "
                "int32 — int32 gathers work everywhere, incl. autograd")
        C = pow2_col_factor(dim)
        if not C:
            # padding to a factorizable length would move data ALONG the
            # big dim — the exact pattern the runtime corrupts
            raise NotImplementedError(
                "take on an odd >int32-range dim: no power-of-two column "
                "factor exists and padding along a >2^31 dim is corrupt "
                "on the TPU runtime; pad the array to an even length at "
                "creation time")
        idx = onp.asarray(indices).astype(onp.int64)
        idx = idx % dim if jmode == "wrap" else onp.clip(idx, 0, dim - 1)
        rows = jnp.asarray((idx // C).astype(onp.int32))
        cols = jnp.asarray((idx % C).astype(onp.int32))
        ck = (a.shape, str(a.dtype), rows.shape)
        fn = _BIG_TAKE_JIT.get(ck)
        if fn is None:

            def big_take(d, r, c):
                # traced: reshape/gathers all carry static metadata
                mat = d.reshape(dim // C, C)
                picked = jnp.take(mat, r, axis=0, mode="clip")
                return jnp.take_along_axis(picked, c[..., None], axis=-1)

            fn = bounded_cache_put(_BIG_TAKE_JIT, ck, jax.jit(big_take))
        return fn(a, rows, cols).reshape(indices.shape)
    # int32 indexing otherwise (indices address an int32-range dim, so
    # every in-bounds value fits int32; out-of-bounds clip/wrap first)
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=jmode)


@register("pick", num_inputs=2)
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    if _concrete_big(data.shape[axis]):
        raise NotImplementedError(
            "pick along a >int32-range dim: the int32 index cast would "
            "silently wrap; reshape so the picked dim fits int32")
    index = index.astype(jnp.int32)
    out = jnp.take_along_axis(data, jnp.expand_dims(index, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd", num_inputs=2)
def gather_nd(data, indices):
    if any(_concrete_big(d) for d in data.shape[:indices.shape[0]]):
        raise NotImplementedError(
            "gather_nd over a >int32-range dim: the int32 index cast "
            "would silently wrap; reshape so indexed dims fit int32")
    idx = tuple(indices.astype(jnp.int32)[i] for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", num_inputs=2, differentiable=True)
def scatter_nd(data, indices, shape=None):
    shape = tuple(shape)
    if any(_concrete_big(d) for d in shape[:indices.shape[0]]):
        raise NotImplementedError(
            "scatter_nd into a >int32-range dim: the int32 index cast "
            "would silently wrap (and scatters along >2^31 dims are "
            "corrupt on the TPU runtime); reshape so scattered dims "
            "fit int32")
    if _base.s64_demoting_backend() and any(
            _concrete_big(d) for d in shape[indices.shape[0]:]):
        # non-indexed dims past int32 range are just as fatal on the TPU
        # runtime: the scatter's row copies move data ALONG the big dim,
        # which lands at corrupt offsets (docs/PERF.md) — refuse rather
        # than write garbage (ADVICE r5); x64-native cpu falls through
        raise NotImplementedError(
            "scatter_nd with a >int32-range non-indexed dim: row copies "
            "along >2^31 dims are corrupt on the TPU runtime; reshape so "
            "every dim of shape fits int32")
    idx = tuple(indices.astype(jnp.int32)[i] for i in range(indices.shape[0]))
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[idx].add(data)


@register("one_hot", differentiable=False)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    eye = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return eye * on_value + (1.0 - eye) * off_value


@register("cast", aliases=["Cast"])
def cast(data, dtype=None):
    return data.astype(jnp.dtype(dtype) if not isinstance(dtype, type) else dtype)


@register("_copy", aliases=["identity", "stop_gradient_copy"])
def _copy(data):
    return jnp.asarray(data)


@register("BlockGrad", aliases=["stop_gradient"], differentiable=False)
def block_grad(data):
    return jax.lax.stop_gradient(data)


@register("where", num_inputs=3)
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


_BIG_SLICE_JIT: dict = {}
_BIG_TAKE_JIT: dict = {}


def _static_slice_index(data, key):
    """Lower static int/slice indexing to one literal-bound lax.slice,
    TRACED under jit.

    For >int32-range dims the default jnp lowering materializes the
    index as an s32/s64 tensor operand — s32 wraps past 2^31 and the
    TPU compiler demotes s64 — and eager execution converts even
    lax.slice to that dynamic form.  Only a slice traced under jit
    keeps its bounds as LITERALS, which compile fine at any offset.
    Returns None for key patterns this cannot express (arrays,
    ellipsis, newaxis, strides)."""
    keys = key if isinstance(key, tuple) else (key,)
    if len(keys) > data.ndim or any(
            isinstance(k, bool) or not isinstance(k, (int, onp.integer, slice))
            for k in keys):
        # bools are ints to isinstance but mean newaxis-like masking in
        # numpy (x[True] -> shape (1, ...)) — never an element index
        return None
    starts, stops, squeeze = [], [], []
    for ax, k in enumerate(keys):
        d = data.shape[ax]
        if isinstance(k, slice):
            s, e, st = k.indices(d)
            if st != 1 or e < s:
                return None
            starts.append(s)
            stops.append(e)
        else:
            i = int(k) + (d if int(k) < 0 else 0)
            starts.append(i)
            stops.append(i + 1)
            squeeze.append(ax)
    for ax in range(len(keys), data.ndim):
        starts.append(0)
        stops.append(data.shape[ax])
    ck = (data.shape, str(data.dtype), tuple(starts), tuple(stops),
          tuple(squeeze))
    fn = _BIG_SLICE_JIT.get(ck)
    if fn is None:

        def do_slice(d):
            out = jax.lax.slice(d, starts, stops)
            if squeeze:
                out = out.reshape([dd for ax2, dd in enumerate(out.shape)
                                   if ax2 not in squeeze])
            return out

        fn = bounded_cache_put(_BIG_SLICE_JIT, ck, jax.jit(do_slice))
    return fn(data)


@register("_index", differentiable=True)
def _index(data, key=None):
    if any(_concrete_big(d) for d in data.shape):
        out = _static_slice_index(data, key)
        if out is not None:
            return out
        if isinstance(key, list) and data.ndim == 1 and key and all(
                isinstance(k, (int, onp.integer)) and not isinstance(k, bool)
                for k in key):
            key = onp.asarray(key, onp.int64)     # list of ints == index array
        # runtime integer-array index on a >int32-range 1-D array: route
        # through take's exact int32 factorization — the default jnp
        # lowering would demote the indices to int32 and gather from
        # wrapped offsets with no error.  Getitem semantics wrap
        # negatives (unlike take's clip), so normalize on host first.
        if (data.ndim == 1 and getattr(key, "dtype", None) is not None
                and onp.dtype(key.dtype).kind in ("i", "u")
                and not isinstance(key, bool)):
            if isinstance(key, jax.core.Tracer):
                raise NotImplementedError(
                    "indexing a >int32-range dim with a traced index "
                    "array (jit/hybridize): the TPU compiler demotes "
                    "s64 index types; index eagerly or use a 2-D view "
                    "whose dims fit int32")
            kh = onp.asarray(key).astype(onp.int64)
            kh = onp.where(kh < 0, kh + data.shape[0], kh)
            return take(data, kh, axis=0, mode="clip")
        # anything else (multi-dim big arrays with array keys, stepped
        # slices, masks) would reach jnp's default lowering, whose int32
        # index demotion silently gathers from wrapped offsets on
        # s64-demoting backends — refuse loudly there; cpu executes s64
        # natively (invoke dispatches it under x64), so fall through
        if jax.default_backend() in S64_DEMOTING_PLATFORMS:
            raise NotImplementedError(
                "this index pattern on a >int32-range dim would be "
                "demoted to int32 by the TPU compiler and gather from "
                "wrapped offsets; use static int/contiguous-slice keys, "
                "a 1-D integer index array, or a 2-D view whose dims "
                "fit int32")
    return data[key]


@register("reverse", aliases=["flip"])
def reverse(data, axis=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axes)


@register("roll")
def roll(data, shift=None, axis=None):
    return jnp.roll(data, shift, axis)


@register("diag")
def diag(data, k=0):
    return jnp.diag(data, k) if data.ndim <= 2 else jnp.diagonal(data, k)


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("sequence_mask", num_inputs=2, aliases=["SequenceMask"])
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    # data: (seq, batch, ...) when axis=0, (batch, seq, ...) when axis=1
    seq_len = data.shape[axis]
    steps = jnp.arange(seq_len)
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
    else:
        mask = steps[None, :] < sequence_length[:, None].astype(jnp.int32)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("sequence_last", num_inputs=2, aliases=["SequenceLast"])
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = -1 if axis == 0 else -1
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
        ).squeeze(0)
    return jnp.take_along_axis(
        data, last.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1
    ).squeeze(1)


@register("sequence_reverse", num_inputs=2, aliases=["SequenceReverse"])
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    seq_len = data.shape[0]
    steps = jnp.arange(seq_len)
    lens = sequence_length.astype(jnp.int32)
    rev_idx = jnp.where(
        steps[:, None] < lens[None, :], lens[None, :] - 1 - steps[:, None], steps[:, None]
    )
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0
    )


@register("shape_array", differentiable=False)
def shape_array(data):
    """int64 like the reference (tensor/elemwise_unary_op.h shape_array).
    Created under a local x64 scope: the global x32 default would silently
    truncate, and a >2**31-element array's size must not wrap."""
    with _base.enable_x64(True):
        return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(data):
    """int64 like the reference (see shape_array)."""
    with _base.enable_x64(True):
        return jnp.asarray([int(onp.prod(data.shape))], dtype=jnp.int64)


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("add_n", num_inputs=-1, aliases=["ElementWiseSum"])
def add_n(arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


@register("dot", num_inputs=2)
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a and lhs.ndim == 2 else lhs
    b = rhs.T if transpose_b and rhs.ndim == 2 else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", num_inputs=2)
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("embedding", num_inputs=2, aliases=["Embedding"])
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None, sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("topk", differentiable=False, num_outputs=-1)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    neg = data if not is_ascend else -data
    vals, idx = jax.lax.top_k(jnp.moveaxis(neg, axis, -1), k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if is_ascend:
        vals = -vals
    if ret_typ == "indices":
        return idx.astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx.astype(jnp.dtype(dtype)))
    if ret_typ == "mask":
        mask = jnp.zeros(jnp.moveaxis(data, axis, -1).shape, dtype=data.dtype)
        idx_last = jnp.moveaxis(idx, axis, -1)
        mask = jnp.put_along_axis(mask, idx_last, 1.0, axis=-1, inplace=False)
        return jnp.moveaxis(mask, -1, axis)
    raise ValueError(f"unknown ret_typ {ret_typ}")


@register("sort", differentiable=False)
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.dtype(dtype))


@register("unique", differentiable=False, num_outputs=-1)
def unique(data):
    return jnp.unique(data, size=None)
