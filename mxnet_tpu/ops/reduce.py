"""Reduction operators (reference ``src/operator/tensor/broadcast_reduce_op*``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, int):
        return axis
    return tuple(axis)


@register("sum", aliases=["sum_axis"])
def sum_op(data, axis=None, keepdims=False, exclude=False):
    axis = _exclude(_norm_axis(axis), data.ndim, exclude)
    return jnp.sum(data, axis=axis, keepdims=keepdims)


def _exclude(axis, ndim, exclude):
    if not exclude or axis is None:
        return axis
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return tuple(i for i in range(ndim) if i not in ax)


@register("mean")
def mean(data, axis=None, keepdims=False, exclude=False):
    axis = _exclude(_norm_axis(axis), data.ndim, exclude)
    return jnp.mean(data, axis=axis, keepdims=keepdims)


@register("prod")
def prod(data, axis=None, keepdims=False, exclude=False):
    axis = _exclude(_norm_axis(axis), data.ndim, exclude)
    return jnp.prod(data, axis=axis, keepdims=keepdims)


@register("nansum")
def nansum(data, axis=None, keepdims=False, exclude=False):
    return jnp.nansum(data, axis=_norm_axis(axis), keepdims=keepdims)


@register("nanprod")
def nanprod(data, axis=None, keepdims=False, exclude=False):
    return jnp.nanprod(data, axis=_norm_axis(axis), keepdims=keepdims)


@register("max", aliases=["max_axis"])
def max_op(data, axis=None, keepdims=False, exclude=False):
    axis = _exclude(_norm_axis(axis), data.ndim, exclude)
    return jnp.max(data, axis=axis, keepdims=keepdims)


@register("min", aliases=["min_axis"])
def min_op(data, axis=None, keepdims=False, exclude=False):
    axis = _exclude(_norm_axis(axis), data.ndim, exclude)
    return jnp.min(data, axis=axis, keepdims=keepdims)


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    axis = _norm_axis(axis)
    if ord == 2:
        return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    raise ValueError("norm only supports ord=1 or 2 (reference parity)")


@register("argmax", differentiable=False)
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("cumsum")
def cumsum(a, axis=None, dtype=None):
    out = jnp.cumsum(a, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("cumprod")
def cumprod(a, axis=None, dtype=None):
    out = jnp.cumprod(a, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise ValueError(mode)
    denom = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / denom
