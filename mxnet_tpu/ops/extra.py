"""Breadth operators: indexing/ravel, krprod, pdf family, regression
outputs, logical/bitwise, linalg-lite, Correlation/PSROIPooling/Proposal.

Reference homes: src/operator/tensor/ravel.cc, contrib/krprod.cc,
contrib/all_finite.cc, random/pdf_op.cc, regression_output.cc,
correlation.cc, contrib/psroi_pooling.cc, contrib/proposal.cc, plus the
numpy elemwise zoo.  Each op is a direct XLA lowering; the loss-layer
``*RegressionOutput`` ops reproduce the reference's special backward
(gradient of the implied loss, independent of the head cotangent) via
``jax.custom_vjp``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import enable_x64 as _enable_x64
from .registry import alias, register

# ---------------------------------------------------------------------------
# indexing / ravel
# ---------------------------------------------------------------------------


@register("unravel_index", num_inputs=1, differentiable=False)
def unravel_index(data, shape=None):
    """Flat indices [N] -> coordinates [ndim, N] (tensor/ravel.cc)."""
    with _enable_x64(True):   # honest int64 (reference ravel.cc)
        coords = jnp.unravel_index(data.astype(jnp.int64), tuple(shape))
    return jnp.stack([c.astype(data.dtype) for c in coords], axis=0)


@register("ravel_multi_index", num_inputs=1, differentiable=False)
def ravel_multi_index(data, shape=None):
    """Coordinates [ndim, N] -> flat indices [N] (tensor/ravel.cc)."""
    shape = tuple(int(s) for s in shape)
    with _enable_x64(True):   # honest int64 (reference ravel.cc)
        idx = 0
        for d, s in enumerate(shape):
            idx = idx * s + data[d].astype(jnp.int64)
    return idx.astype(data.dtype)


@register("batch_take", num_inputs=2, differentiable=False)
def batch_take(a, indices):
    """a [N, M] taken at per-row column index [N] (tensor/indexing_op.cc)."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("choose_element_0index", num_inputs=2, differentiable=False)
def choose_element_0index(data, index):
    return batch_take(data, index)


@register("fill_element_0index", num_inputs=3, differentiable=False)
def fill_element_0index(lhs, mhs, rhs):
    """lhs[i, rhs[i]] = mhs[i] (legacy top-level op)."""
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, rhs.astype(jnp.int32)].set(mhs)


@register("Crop", num_inputs=-1, differentiable=True)
def crop(arrays, num_args=1, offset=(0, 0), h_w=(0, 0), center_crop=False):
    """Legacy Crop op (src/operator/crop.cc): crop arrays[0] to the size of
    arrays[1] (or h_w) at ``offset`` / center."""
    data = arrays[0]
    H, W = data.shape[2], data.shape[3]
    if len(arrays) > 1:
        th, tw = arrays[1].shape[2], arrays[1].shape[3]
    else:
        th, tw = h_w
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = offset
    return data[:, :, y0:y0 + th, x0:x0 + tw]


# ---------------------------------------------------------------------------
# krprod / all_finite
# ---------------------------------------------------------------------------


@register("khatri_rao", num_inputs=-1)
def khatri_rao(arrays):
    """Column-wise Kronecker product (contrib/krprod.cc)."""
    out = arrays[0]
    for a in arrays[1:]:
        out = (out[:, None, :] * a[None, :, :]).reshape(-1, out.shape[1])
    return out


@register("all_finite", num_inputs=1, differentiable=False)
def all_finite(data, init_output=True):
    """1.0 if every element is finite (contrib/all_finite.cc) -> [1]."""
    return jnp.all(jnp.isfinite(data)).astype(jnp.float32).reshape(1)


@register("multi_all_finite", num_inputs=-1, differentiable=False)
def multi_all_finite(arrays, num_arrays=0, init_output=True):
    ok = jnp.asarray(True)
    for a in arrays:
        ok &= jnp.all(jnp.isfinite(a))
    return ok.astype(jnp.float32).reshape(1)


# ---------------------------------------------------------------------------
# regression output loss layers (src/operator/regression_output.cc):
# forward is the prediction; backward is the loss gradient wrt data,
# INDEPENDENT of the incoming cotangent (the reference treats these as
# terminal loss nodes).
# ---------------------------------------------------------------------------


def _regression_output(name, fwd_fn, grad_fn):
    import functools

    @functools.lru_cache(maxsize=None)
    def core_for(scale):
        @jax.custom_vjp
        def core(data, label):
            return fwd_fn(data)

        def fwd(data, label):
            return fwd_fn(data), (data, label)

        def bwd(res, ct):
            data, label = res
            g = grad_fn(data, label) * scale
            return (g.astype(data.dtype), jnp.zeros_like(label))

        core.defvjp(fwd, bwd)
        return core

    def op(data, label, grad_scale=1.0):
        return core_for(float(grad_scale))(data, label)

    op.__name__ = name
    return op


@register("LinearRegressionOutput", num_inputs=2,
          aliases=["linear_regression_output"])
def linear_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward = (data - label) * grad_scale."""
    return _lin_reg(data, label, grad_scale)


_lin_reg = _regression_output(
    "LinearRegressionOutput", lambda d: d, lambda d, l: d - l)


@register("MAERegressionOutput", num_inputs=2,
          aliases=["mae_regression_output"])
def mae_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward = sign(data - label) * grad_scale."""
    return _mae_reg(data, label, grad_scale)


_mae_reg = _regression_output(
    "MAERegressionOutput", lambda d: d, lambda d, l: jnp.sign(d - l))


@register("LogisticRegressionOutput", num_inputs=2,
          aliases=["logistic_regression_output"])
def logistic_regression_output(data, label, grad_scale=1.0):
    """Sigmoid forward; backward = (sigmoid(data) - label) * grad_scale."""
    return _log_reg(data, label, grad_scale)


_log_reg = _regression_output(
    "LogisticRegressionOutput", jax.nn.sigmoid,
    lambda d, l: jax.nn.sigmoid(d) - l)


# ---------------------------------------------------------------------------
# pdf family (src/operator/random/pdf_op.cc): elementwise densities of the
# sampling ops, differentiable wrt sample AND parameters
# ---------------------------------------------------------------------------


def _maybe_log(p_log, is_log):
    return p_log if is_log else jnp.exp(p_log)


@register("pdf_normal", num_inputs=3)
def pdf_normal(sample, mu, sigma, is_log=False):
    logp = -0.5 * jnp.square((sample - mu) / sigma) \
        - jnp.log(sigma) - 0.5 * jnp.log(2 * jnp.pi)
    return _maybe_log(logp, is_log)


@register("pdf_uniform", num_inputs=3)
def pdf_uniform(sample, low, high, is_log=False):
    inside = (sample >= low) & (sample <= high)
    logp = jnp.where(inside, -jnp.log(high - low), -jnp.inf)
    return _maybe_log(logp, is_log)


@register("pdf_gamma", num_inputs=3)
def pdf_gamma(sample, alpha, beta, is_log=False):
    logp = alpha * jnp.log(beta) + (alpha - 1) * jnp.log(sample) \
        - beta * sample - jax.lax.lgamma(alpha)
    return _maybe_log(logp, is_log)


@register("pdf_exponential", num_inputs=2)
def pdf_exponential(sample, lam, is_log=False):
    logp = jnp.log(lam) - lam * sample
    return _maybe_log(logp, is_log)


@register("pdf_poisson", num_inputs=2)
def pdf_poisson(sample, lam, is_log=False):
    logp = sample * jnp.log(lam) - lam - jax.lax.lgamma(sample + 1.0)
    return _maybe_log(logp, is_log)


@register("pdf_negative_binomial", num_inputs=3)
def pdf_negative_binomial(sample, k, p, is_log=False):
    logp = jax.lax.lgamma(sample + k) - jax.lax.lgamma(sample + 1.0) \
        - jax.lax.lgamma(k) + k * jnp.log(p) + sample * jnp.log1p(-p)
    return _maybe_log(logp, is_log)


@register("pdf_generalized_negative_binomial", num_inputs=3)
def pdf_generalized_negative_binomial(sample, mu, alpha, is_log=False):
    k = 1.0 / alpha
    p = k / (k + mu)
    return pdf_negative_binomial(sample, k, p, is_log=is_log)


@register("pdf_dirichlet", num_inputs=2)
def pdf_dirichlet(sample, alpha, is_log=False):
    logp = jnp.sum((alpha - 1.0) * jnp.log(sample), axis=-1) \
        + jax.lax.lgamma(jnp.sum(alpha, axis=-1)) \
        - jnp.sum(jax.lax.lgamma(alpha), axis=-1)
    return _maybe_log(logp, is_log)


# ---------------------------------------------------------------------------
# logical / bitwise / numpy-elemwise leftovers
# ---------------------------------------------------------------------------


@register("logical_and", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def logical_and(lhs, rhs):
    return ((lhs != 0) & (rhs != 0)).astype(lhs.dtype)


@register("logical_or", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def logical_or(lhs, rhs):
    return ((lhs != 0) | (rhs != 0)).astype(lhs.dtype)


@register("logical_xor", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def logical_xor(lhs, rhs):
    return ((lhs != 0) ^ (rhs != 0)).astype(lhs.dtype)


@register("bitwise_and", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def bitwise_and(lhs, rhs):
    with _enable_x64(True):   # int64 semantics without x32 truncation
        return jnp.bitwise_and(lhs.astype(jnp.int64),
                               rhs.astype(jnp.int64)).astype(lhs.dtype)


@register("bitwise_or", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def bitwise_or(lhs, rhs):
    with _enable_x64(True):
        return jnp.bitwise_or(lhs.astype(jnp.int64),
                              rhs.astype(jnp.int64)).astype(lhs.dtype)


@register("bitwise_xor", num_inputs=2, differentiable=False,
          namespaces=("nd", "np"))
def bitwise_xor(lhs, rhs):
    with _enable_x64(True):
        return jnp.bitwise_xor(lhs.astype(jnp.int64),
                               rhs.astype(jnp.int64)).astype(lhs.dtype)


@register("bitwise_not", num_inputs=1, differentiable=False,
          aliases=["invert"], namespaces=("nd", "np"))
def bitwise_not(data):
    with _enable_x64(True):
        return jnp.bitwise_not(data.astype(jnp.int64)).astype(data.dtype)


@register("digamma", num_inputs=1)
def digamma(data):
    return jax.lax.digamma(data)


@register("hypot", num_inputs=2, namespaces=("nd", "np"))
def hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@register("ldexp", num_inputs=2, namespaces=("nd", "np"))
def ldexp(lhs, rhs):
    return lhs * jnp.power(2.0, rhs)


@register("logaddexp", num_inputs=2, namespaces=("nd", "np"))
def logaddexp(lhs, rhs):
    return jnp.logaddexp(lhs, rhs)


@register("triu", num_inputs=1, namespaces=("nd", "np"))
def triu(data, k=0):
    return jnp.triu(data, k=k)


@register("tril", num_inputs=1, namespaces=("nd", "np"))
def tril(data, k=0):
    return jnp.tril(data, k=k)


@register("trace", num_inputs=1, namespaces=("nd", "np"))
def trace(data, offset=0, axis1=0, axis2=1):
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@register("rot90", num_inputs=1, namespaces=("nd", "np"))
def rot90(data, k=1, axes=(0, 1)):
    return jnp.rot90(data, k=k, axes=tuple(axes))


# ---------------------------------------------------------------------------
# Correlation (src/operator/correlation.cc — FlowNet cost volume)
# ---------------------------------------------------------------------------


@register("Correlation", num_inputs=2, aliases=["correlation"])
def correlation_op(data1, data2, kernel_size=1, max_displacement=1,
                   stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """Cost volume between two feature maps [B,C,H,W] ->
    [B, D*D, Ho, Wo] where D = 2*(max_displacement//stride2)+1; each
    channel is the kernel-window correlation at one displacement."""
    B, C, H, W = data1.shape
    p = pad_size
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    br = kernel_size // 2
    sr = max_displacement // stride2
    D = 2 * sr + 1
    Hp, Wp = H + 2 * p, W + 2 * p
    # output grid (centers where the full neighborhood fits)
    b0 = br + max_displacement
    Ho = int(jnp.ceil((Hp - b0 * 2) / stride1))
    Wo = int(jnp.ceil((Wp - b0 * 2) / stride1))
    ys = b0 + jnp.arange(Ho) * stride1
    xs = b0 + jnp.arange(Wo) * stride1
    # the D*D displacement axis is vmapped (one rolled gather body) so the
    # traced graph stays small at FlowNet-scale max_displacement; only the
    # tiny kernel window is unrolled
    disp = jnp.asarray([(dy, dx)
                        for dy in range(-sr, sr + 1)
                        for dx in range(-sr, sr + 1)], jnp.int32)

    def one_disp(d):
        dy, dx = d[0] * stride2, d[1] * stride2
        acc = 0.0
        for ky in range(-br, br + 1):
            for kx in range(-br, br + 1):
                a = d1[:, :, ys[:, None] + ky, xs[None, :] + kx]
                b = d2[:, :, ys[:, None] + ky + dy, xs[None, :] + kx + dx]
                acc = acc + (a * b if is_multiply else jnp.abs(a - b))
        return jnp.sum(acc, axis=1)          # [B, Ho, Wo]

    out = jnp.moveaxis(jax.vmap(one_disp)(disp), 0, 1)  # [B, D*D, Ho, Wo]
    return out / (kernel_size * kernel_size * C)


# ---------------------------------------------------------------------------
# PSROIPooling + Proposal (contrib/psroi_pooling.cc, contrib/proposal.cc)
# ---------------------------------------------------------------------------


@register("PSROIPooling", num_inputs=2, aliases=["psroipooling"])
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1, pooled_size=1,
                  group_size=0):
    """Position-sensitive ROI pooling (R-FCN): data [B, output_dim*ps*ps,
    H, W], rois [R,5] (batch_idx, x1, y1, x2, y2 in image coords) ->
    [R, output_dim, ps, ps]; bin (i,j) average-pools its OWN channel
    group."""
    ps = int(pooled_size)
    gs = int(group_size) or ps
    B, CT, H, W = data.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / ps, rh / ps
        img = data[b].reshape(output_dim, gs * gs, H, W)
        cells = []
        S = 2  # fixed sub-samples per bin (XLA-friendly static count)
        for i in range(ps):
            for j in range(ps):
                gy = min(i * gs // ps, gs - 1)
                gx = min(j * gs // ps, gs - 1)
                chan = img[:, gy * gs + gx]
                ysub = y1 + bh * (i + (jnp.arange(S) + 0.5) / S)
                xsub = x1 + bw * (j + (jnp.arange(S) + 0.5) / S)
                yi = jnp.clip(ysub, 0, H - 1).astype(jnp.int32)
                xi = jnp.clip(xsub, 0, W - 1).astype(jnp.int32)
                patch = chan[:, yi][:, :, xi]
                cells.append(jnp.mean(patch, axis=(1, 2)))
        return jnp.stack(cells, axis=-1).reshape(output_dim, ps, ps)

    return jax.vmap(one)(rois)


@register("Proposal", num_inputs=3, differentiable=False,
          aliases=["proposal"])
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal op (contrib/proposal.cc): decode per-anchor deltas,
    clip to image, drop tiny boxes, NMS, keep top-k -> rois [B*K, 5]."""
    B, A2, Hf, Wf = cls_prob.shape
    A = A2 // 2
    # base anchors centered at (fs/2 - .5) like the reference's generator
    fs = float(feature_stride)
    base = []
    cx = cy = (fs - 1) / 2
    for r in ratios:
        size = fs * fs
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            w2, h2 = ws * s / 2, hs * s / 2
            base.append([cx - w2 + 0.5, cy - h2 + 0.5,
                         cx + w2 - 0.5, cy + h2 - 0.5])
    base = jnp.asarray(base, jnp.float32)[:A]       # [A,4]
    sx = jnp.arange(Wf, dtype=jnp.float32) * fs
    sy = jnp.arange(Hf, dtype=jnp.float32) * fs
    shift = jnp.stack(
        [sx[None, :].repeat(Hf, 0).reshape(-1),
         sy[:, None].repeat(Wf, 1).reshape(-1)] * 2, axis=-1)  # [H*W,4]
    anchors = (base[None] + shift[:, None]).reshape(-1, 4)     # [H*W*A,4]
    N = anchors.shape[0]
    K = int(rpn_post_nms_top_n)

    def one(scores, deltas, info):
        fg = scores[A:].reshape(A, -1).T.reshape(-1)     # [H*W*A]
        dl = deltas.reshape(A, 4, Hf * Wf).transpose(2, 0, 1).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        ax = anchors[:, 0] + aw / 2
        ay = anchors[:, 1] + ah / 2
        px = dl[:, 0] * aw + ax
        py = dl[:, 1] * ah + ay
        pw = jnp.exp(jnp.clip(dl[:, 2], -10, 10)) * aw
        phh = jnp.exp(jnp.clip(dl[:, 3], -10, 10)) * ah
        boxes = jnp.stack([px - pw / 2, py - phh / 2,
                           px + pw / 2, py + phh / 2], axis=-1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=-1)
        ok = ((boxes[:, 2] - boxes[:, 0] + 1 >= rpn_min_size * info[2])
              & (boxes[:, 3] - boxes[:, 1] + 1 >= rpn_min_size * info[2]))
        fg = jnp.where(ok, fg, -1.0)
        rows = jnp.concatenate([jnp.zeros((N, 1)), fg[:, None], boxes],
                               axis=-1)
        from .detection import _nms_single

        kept = _nms_single(rows.astype(jnp.float32), threshold, 0.0,
                           int(rpn_pre_nms_top_n), 2, 1, -1, -1, True,
                           "corner", "corner")
        return kept[:K, 2:6], kept[:K, 1]

    rois, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(B, dtype=jnp.float32), K)[:, None]
    rois_flat = jnp.concatenate([bidx, rois.reshape(-1, 4)], axis=-1)
    if output_score:
        return rois_flat, scores.reshape(-1, 1)
    return rois_flat


@register("sldwin_atten_mask_like", num_inputs=2, differentiable=False)
def sldwin_atten_mask_like(data, valid_length, w=4, symmetric=True):
    """Sliding-window attention mask (contrib/transformer.cc sldwin ops,
    BERT long-sequence path): ones where |i-j| <= w (and j <= i when not
    symmetric), zeros elsewhere / beyond valid_length."""
    S = data.shape[-2] if data.ndim >= 2 else data.shape[0]
    i = jnp.arange(S)[:, None]
    j = jnp.arange(S)[None, :]
    win = (j >= i - w) & ((j <= i + w) if symmetric else (j <= i))
    mask = win.astype(data.dtype)
    if valid_length is not None:
        vl = valid_length.reshape(-1, 1, 1)
        mask = mask[None] * (j[None] < vl) * (i[None] < vl)
    return jnp.broadcast_to(mask, data.shape[:-2] + (S, S)) \
        if data.ndim > 2 else mask


@register("matmul", num_inputs=2, namespaces=("nd", "np"))
def matmul(a, b):
    """N-D broadcasting matmul (reference numpy/np_matmul_op.cc
    _npi_matmul; also the ONNX MatMul lowering target)."""
    return jnp.matmul(a, b)


alias("max", "amax")
alias("min", "amin")
alias("SliceChannel", "slice_channel")


@register("RROIAlign", num_inputs=2, aliases=("_contrib_RROIAlign",))
def rroi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sampling_ratio=2):
    """Rotated ROI Align (reference src/operator/contrib/rroi_align.cc:149):
    rois (R, 6) = [batch_index, x_center, y_center, w, h, theta_degrees];
    the pooled grid is generated in the box frame, rotated by theta about
    the center, and bilinearly sampled."""
    ph, pw = pooled_size
    n, c, H, W = data.shape
    sr = max(int(sampling_ratio), 1)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * jnp.pi / 180.0
        cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
        # sample points in the box-local frame, sr x sr per bin
        ys = (jnp.arange(ph * sr) + 0.5) / (ph * sr) - 0.5   # [-.5, .5)
        xs = (jnp.arange(pw * sr) + 0.5) / (pw * sr) - 0.5
        ly = ys[:, None] * rh                                # (ph*sr, 1)
        lx = xs[None, :] * rw                                # (1, pw*sr)
        gx = cx + lx * cos_t - ly * sin_t                    # rotate
        gy = cy + lx * sin_t + ly * cos_t
        gx = jnp.broadcast_to(gx, (ph * sr, pw * sr))
        gy = jnp.broadcast_to(gy, (ph * sr, pw * sr))
        # reference rroi_align.cc bilinear_interpolate: sample points
        # outside [-1, W] x [-1, H] contribute ZERO (not edge replication)
        valid = ((gx > -1.0) & (gx < W) & (gy > -1.0) & (gy < H))
        gxc = jnp.clip(gx, 0, W - 1)
        gyc = jnp.clip(gy, 0, H - 1)
        x0 = jnp.floor(gxc).astype(jnp.int32)
        y0 = jnp.floor(gyc).astype(jnp.int32)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        wx = gxc - x0
        wy = gyc - y0
        img = data[b]                                        # (c, H, W)
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1]
        v10 = img[:, y1, x0]
        v11 = img[:, y1, x1]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)        # (c, ph*sr, pw*sr)
        val = val * valid[None].astype(val.dtype)
        val = val.reshape(c, ph, sr, pw, sr)
        return val.mean(axis=(2, 4))                         # (c, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("edge_id", num_inputs=3, differentiable=False,
          aliases=("_contrib_edge_id",))
def edge_id(adjacency, u, v):
    """Edge-id lookup (reference src/operator/contrib/dgl_graph.cc
    _contrib_edge_id over CSR): ``adjacency`` is a dense adjacency whose
    entries hold edge-id + 1 (0 = no edge); returns the edge id for each
    (u[i], v[i]) pair, -1 where absent.  CSR containers densify through
    ``.todense()`` at the frontend."""
    vals = adjacency[u.astype(jnp.int32), v.astype(jnp.int32)]
    with _enable_x64(True):   # reference returns int64 edge ids
        return jnp.where(vals > 0, vals - 1, -1).astype(jnp.int64)


@register("sparse_retain", num_inputs=2, differentiable=False,
          aliases=("_sparse_retain",))
def sparse_retain(data, indices):
    """Keep only the listed rows, zero the rest (reference
    src/operator/tensor/sparse_retain.cc over row_sparse; dense layout
    here — the row_sparse container wraps this at the NDArray level)."""
    keep = jnp.zeros((data.shape[0],), jnp.bool_).at[
        indices.astype(jnp.int32)].set(True)
    shape = (-1,) + (1,) * (data.ndim - 1)
    return jnp.where(keep.reshape(shape), data, 0)
