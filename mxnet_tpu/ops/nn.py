"""Neural-network operators.

Reference analog: ``src/operator/nn/`` (~31k LoC of CPU/cuDNN kernels).  On
TPU each op is a lax/jnp composition; XLA lowers convolutions and matmuls
onto the MXU and picks algorithms automatically (the reference needed the
cuDNN algo-registry ``src/operator/nn/cudnn/cudnn_algoreg-inl.h`` for that).

Layout note: MXNet defaults to NCHW.  These ops accept a ``layout`` attr and
pass it straight to XLA dimension numbers — on TPU, NHWC keeps the channel
dim minor and maps best onto the MXU, so the Gluon layers default to
computing in NHWC internally while presenting NCHW at the API boundary.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from .registry import register


# --- activations -----------------------------------------------------------

@register("relu")
def relu(data):
    return jax.nn.relu(data)


@register("sigmoid")
def sigmoid(data):
    return jax.nn.sigmoid(data)


@register("log_sigmoid")
def log_sigmoid(data):
    return jax.nn.log_sigmoid(data)


@register("softrelu")
def softrelu(data):
    return jax.nn.softplus(data)


@register("softsign")
def softsign(data):
    return jax.nn.soft_sign(data)


@register("mish")
def mish(data):
    return data * jnp.tanh(jax.nn.softplus(data))


@register("hard_sigmoid")
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("Activation")
def activation(data, act_type="relu"):
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "log_sigmoid": jax.nn.log_sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    }
    return fns[act_type](data)


@register("LeakyReLU", num_inputs=-1)
def leaky_relu(arrays, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    data = arrays[0]
    if act_type == "leaky":
        return jax.nn.leaky_relu(data, slope)
    if act_type == "prelu":
        gamma = arrays[1]
        # broadcast gamma over channel axis 1
        shape = [1] * data.ndim
        if gamma.ndim == 1 and data.ndim > 1:
            shape[1] = gamma.shape[0]
            gamma = gamma.reshape(shape)
        return jnp.where(data >= 0, data, gamma * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        return jax.nn.selu(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(f"unknown act_type {act_type}")


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    x = data / temperature if temperature else data
    if length is not None:
        steps = jnp.arange(x.shape[axis])
        mask = steps < length[..., None].astype(jnp.int32)
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, axis=-1, temperature=None):
    x = -data / temperature if temperature else -data
    return jax.nn.softmax(x, axis=axis)


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(
        jnp.abs(data) < 1.0 / s2,
        0.5 * s2 * jnp.square(data),
        jnp.abs(data) - 0.5 / s2,
    )


# --- dense / conv ----------------------------------------------------------

@register("FullyConnected", num_inputs=-1, aliases=["fully_connected"])
def fully_connected(arrays, num_hidden=0, no_bias=False, flatten=True,
                    fused_relu=False):
    """data (N, ...), weight (num_hidden, in_units) — reference
    src/operator/nn/fully_connected.cc.  ``fused_relu`` is set by the
    int8 graph pass when a following relu folded into this node."""
    data, weight = arrays[0], arrays[1]
    x = data.reshape(data.shape[0], -1) if flatten else data
    out = jnp.matmul(x, weight.T)
    if not no_bias:
        out = out + arrays[2]
    return jnp.maximum(out, 0) if fused_relu else out


def _conv_dimension_numbers(layout: str):
    # layouts: NCW/NWC, NCHW/NHWC, NCDHW/NDHWC; weight is O + (spatial|I) per layout
    spatial = layout.replace("N", "").replace("C", "")
    if layout.index("C") == 1:
        w = "OI" + spatial
    else:
        w = "O" + spatial + "I"
    return (layout, w, layout)


# --- MXU-alignment padding pass (round 9, ROADMAP item 2) -------------------
#
# Staged convolutions whose channel axes miss the TPU tile quanta (the
# cin=3 stem, odd-channel heads) underfill the MXU contraction.  The pass
# zero-pads Cin on BOTH operands (each padded tap contributes exactly
# 0.0 — IEEE x + 0.0 == x, so the kept lanes are bit-exact) and pads
# Cout with slice-back (output channels are independent dots, so the
# kept channels are computed identically).  It runs ONLY at trace time
# (Tracer-gated, like the conv+BN producer tag), so the pad/slice are
# part of the compiled program keyed by the UNPADDED input shapes —
# 0 added retraces and 0 added dispatches per step by construction; XLA
# folds the pads into the surrounding layout work.  This generalizes the
# stem_s2d idea (re-shaping conv0 onto the MXU) to every misaligned
# conv.  Quanta: the sublane quantum of the operand dtype — 8 for
# fp32/bf16, 32 for int8 (the int8 path applies it in
# contrib/quantization.py quantized_conv).  Bit-exactness is asserted by
# tools/check_fusion_budget.py and tests/test_fused_epilogue.py.

from .. import telemetry as _telemetry  # noqa: E402

_PAD_CHANNELS = _telemetry.counter(
    "nn.pad_channels", "convolutions the MXU-alignment pass padded "
    "(trace-time: one per padded conv node per trace)")


def pad_channels_count() -> int:
    """Convolutions the MXU-alignment pass padded (trace-time count:
    one per padded conv node per trace).  View over the
    ``nn.pad_channels`` telemetry counter."""
    return int(_PAD_CHANNELS.value)


def _pad_up(v: int, q: int) -> int:
    return -(-v // q) * q


def maybe_pad_conv_channels(data, weight, layout: str, num_group: int):
    """Apply the MXU-alignment padding pass when eligible: returns
    ``(padded_data, padded_weight, true_cout)`` or ``None`` (aligned
    already, knob off, eager call, or grouped conv)."""
    from .. import config as _config

    mode = _config.get("MXNET_PAD_CHANNELS")
    if not mode or num_group != 1:
        return None
    if mode != 2 and jax.default_backend() != "tpu":
        return None
    if not isinstance(data, jax.core.Tracer):
        return None                      # staging-layer pass: eager
    c_axis = layout.index("C")           # dispatch never pays the pads
    cin = int(data.shape[c_axis])
    cout = int(weight.shape[0])
    q = 32 if jnp.dtype(data.dtype).itemsize == 1 else 8
    cin_p, cout_p = _pad_up(cin, q), _pad_up(cout, q)
    if cin_p == cin and cout_p == cout:
        return None
    w_in_axis = 1 if c_axis == 1 else weight.ndim - 1
    dpad = [(0, 0)] * data.ndim
    dpad[c_axis] = (0, cin_p - cin)
    wpad = [(0, 0)] * weight.ndim
    wpad[0] = (0, cout_p - cout)
    wpad[w_in_axis] = (0, cin_p - cin)
    _PAD_CHANNELS.inc()
    return (jnp.pad(data, dpad) if cin_p != cin else data,
            jnp.pad(weight, wpad), cout)


def _tup(v, n):
    if v is None:
        return (0,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution", num_inputs=-1, aliases=["conv"])
def convolution(arrays, kernel=None, stride=None, dilate=None, pad=None,
                num_filter=0, num_group=1, no_bias=False, layout=None,
                workspace=None, cudnn_tune=None, cudnn_off=None,
                fused_relu=False):
    """N-D convolution (reference src/operator/nn/convolution.cc).

    XLA handles algorithm selection/tiling; ``workspace``/``cudnn_*`` attrs
    are accepted for API parity and ignored.
    """
    data, weight = arrays[0], arrays[1]
    nsp = len(kernel)
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nsp]
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    dilate = _tup(dilate, nsp) if dilate else (1,) * nsp
    pad = _tup(pad, nsp)
    c_axis = layout.index("C")
    true_cout = None
    padded = maybe_pad_conv_channels(data, weight, layout, num_group)
    if padded is not None:
        data, weight, true_cout = padded
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, _conv_dimension_numbers(layout)
    )
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if true_cout is not None and out.shape[c_axis] != true_cout:
        out = jax.lax.slice_in_dim(out, 0, true_cout, axis=c_axis)
    if not no_bias:
        bias = arrays[2]
        shape = [1] * out.ndim
        shape[c_axis] = bias.shape[0]
        out = out + bias.reshape(shape)
    return jnp.maximum(out, 0) if fused_relu else out


@register("Deconvolution", num_inputs=-1)
def deconvolution(arrays, kernel=None, stride=None, dilate=None, pad=None,
                  adj=None, target_shape=None, num_filter=0, num_group=1,
                  no_bias=True, layout=None, workspace=None, cudnn_tune=None,
                  cudnn_off=None):
    """Transposed convolution (reference src/operator/nn/deconvolution.cc)."""
    data, weight = arrays[0], arrays[1]
    nsp = len(kernel)
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nsp]
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    dilate = _tup(dilate, nsp) if dilate else (1,) * nsp
    pad = _tup(pad, nsp)
    adj = _tup(adj, nsp) if adj else (0,) * nsp
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, _conv_dimension_numbers(layout)
    )
    # gradient-of-conv == transposed conv: lhs_dilation by stride
    k_eff = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    padding = [
        (ke - 1 - p, ke - 1 - p + a) for ke, p, a in zip(k_eff, pad, adj)
    ]
    # weight layout for deconv in MXNet is (in_c, out_c/g, *kernel): flip to OIHW
    c_axis = layout.index("C")
    if c_axis == 1:
        w = jnp.swapaxes(weight, 0, 1)
        w = jnp.flip(w, axis=tuple(range(2, 2 + nsp)))
    else:
        # channel-last: weight (in_c, *kernel, out_c) -> 'O'+spatial+'I'
        w = jnp.swapaxes(weight, 0, -1)
        w = jnp.flip(w, axis=tuple(range(1, 1 + nsp)))
    out = jax.lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * nsp,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias:
        bias = arrays[2]
        shape = [1] * out.ndim
        shape[c_axis] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


# --- pooling ---------------------------------------------------------------

@register("Pooling")
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, layout=None, cudnn_off=None, p_value=2):
    """Reference src/operator/nn/pooling.cc."""
    nsp = data.ndim - 2
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nsp]
    sp_axes = tuple(i for i, c in enumerate(layout) if c not in "NC")
    if global_pool:
        if pool_type == "max":
            return jnp.max(data, axis=sp_axes, keepdims=True)
        return jnp.mean(data, axis=sp_axes, keepdims=True)
    kernel = _tup(kernel, nsp)
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    pad = _tup(pad, nsp)

    window = [1] * data.ndim
    strides = [1] * data.ndim
    padding = [(0, 0)] * data.ndim
    for ax, k, s, p in zip(sp_axes, kernel, stride, pad):
        window[ax] = k
        strides[ax] = s
        padding[ax] = (p, p)

    if pooling_convention == "full":
        # ceil-mode: extend right padding so last window fits
        for i, ax in enumerate(sp_axes):
            size = data.shape[ax] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                extra = stride[i] - rem
                padding[ax] = (pad[i], pad[i] + extra)

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(
            data, init, jax.lax.max, window, strides, padding
        )
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(
            data, 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0,
            jax.lax.add, window, strides, padding
        )
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = float(onp.prod(kernel))
            return summed / jnp.asarray(denom, data.dtype)
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, window, strides, padding
        )
        return summed / counts
    if pool_type == "lp":
        powed = jax.lax.reduce_window(
            jnp.abs(data) ** p_value, 0.0, jax.lax.add, window, strides, padding
        )
        return powed ** (1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


# --- normalization ---------------------------------------------------------

@register("BatchNorm", num_inputs=-1, num_outputs=-1)
def batch_norm(arrays, eps=1e-3, momentum=0.9, fix_gamma=True,
               use_global_stats=False, output_mean_var=False, axis=1,
               cudnn_off=None, training=False):
    """Reference src/operator/nn/batch_norm.cc.

    Returns out (+ batch mean/var when training so the layer can update
    running stats functionally — the reference mutated aux states in-place).
    """
    data, gamma, beta, moving_mean, moving_var = arrays
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if training and not use_global_stats:
        # Single-pass batch stats: E[x] and E[x^2] reduce the SAME operand,
        # which XLA fuses into one multi-output reduction (one HBM read of
        # the activation instead of the 2-3 passes mean-then-var costs).
        # Accumulate fp32 even for bf16 activations — the convert fuses
        # into the reduction, and the reduction still READS bf16 from HBM
        # (half the bandwidth of an fp32 materialization).
        #
        # Precision note: E[x^2]-E[x]^2 cancels when |mean| >> std (fp32
        # error ~ mean^2 * 2^-24 absolute).  This is the standard TPU BN
        # formulation (flax.linen.BatchNorm computes exactly this) and is
        # safe for normalized activations; pathological activation scales
        # can set MXNET_BN_TWO_PASS_VAR=1 to restore the two-pass
        # shifted variance at one extra HBM pass.
        from .. import config as _config
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red_axes)
        if _config.get("MXNET_BN_TWO_PASS_VAR"):
            var = jnp.var(x32, axis=red_axes)
        else:
            meansq = jnp.mean(x32 * x32, axis=red_axes)
            var = jnp.maximum(meansq - mean * mean, 0.0)
    else:
        mean, var = moving_mean, moving_var
    # Fold the affine into per-channel scale/bias vectors (C-sized, fp32):
    # the big tensor then sees ONE fused multiply-add in its own dtype.
    f32 = jnp.float32
    inv = jax.lax.rsqrt(var.astype(f32) + f32(eps))
    sc = inv * g.astype(f32)
    bi = beta.astype(f32) - mean.astype(f32) * sc
    out = data * sc.reshape(shape).astype(data.dtype) \
        + bi.reshape(shape).astype(data.dtype)
    if training and not use_global_stats:
        return (out, mean.astype(moving_mean.dtype),
                var.astype(moving_var.dtype))
    return (out,)


def _fused_bn_epilogue(z, mean, var, gamma, beta, b, eps, fix_gamma):
    """Shared normalize for the fused conv+BN ops.  Normalizes against
    the bias-FREE z with the bias-free mean (the conv bias cancels in
    (z + b) - (mean + b); this is also ~16x more fp32-accurate than
    stats on the shifted z — see tests/test_fused_conv_bn.py::
    test_biased_conv_fuses_exactly), then folds the bias into the
    returned mean so running statistics — hence inference — see the
    biased conv exactly."""
    f32 = jnp.float32
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = jax.lax.rsqrt(var + f32(eps))            # mean/var already fp32
    sc = inv * g.astype(f32)
    bi = beta.astype(f32) - mean * sc
    out = z * sc.astype(z.dtype) + bi.astype(z.dtype)
    if b is not None:
        mean = mean + b.astype(f32)
    return out, mean, var


@register("_fused_conv1x1_bn", num_inputs=-1, num_outputs=-1)
def fused_conv1x1_bn(arrays, stride=(1, 1), eps=1e-5, fix_gamma=False,
                     has_bias=False):
    """Training-mode 1x1-conv + BatchNorm with the batch statistics computed
    in the conv's Pallas epilogue (ops/pallas_kernels.py
    conv1x1_bn_stats_train) — one HBM pass over the conv output instead of
    conv-write-then-stats-read.  NHWC x, OHWI w.  Strided 1x1 convs
    pre-slice the input (exact: a 1x1 kernel never straddles the stride).
    A conv bias shifts z and the batch mean EQUALLY, so the normalized
    output is bias-invariant; the bias is folded only into the returned
    mean (keeping running statistics — hence inference — exact).
    Returns (out, batch_mean, batch_var) like BatchNorm(training=True).
    No reference analog (src/operator/nn/batch_norm.cc stats are a separate
    pass) — TPU-first fusion; the gluon BatchNorm layer routes here, see
    gluon/nn/basic_layers.py."""
    from .pallas_kernels import conv1x1_bn_stats_train

    if has_bias:
        x, w, b, gamma, beta = arrays
    else:
        x, w, gamma, beta = arrays
        b = None
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    z, mean, var = conv1x1_bn_stats_train(x, w)
    return _fused_bn_epilogue(z, mean, var, gamma, beta, b, eps, fix_gamma)


@register("_fused_convkxk_bn", num_inputs=-1, num_outputs=-1,
          aliases=("_fused_conv3x3_bn",))
def fused_convkxk_bn(arrays, eps=1e-5, fix_gamma=False, has_bias=False,
                     pad=(1, 1)):
    """Training-mode KxK/stride-1 conv + BatchNorm with batch statistics
    in the conv's Pallas epilogue (ops/pallas_kernels.py
    convkxk_bn_stats_train; full-image VMEM tiles, KxK shifted MXU
    matmuls).  Covers the 3x3/pad-1 bottleneck sites AND the s2d stem's
    4x4/pad-0 conv (the network's LARGEST activation and biggest single
    BN-stats read).  Bias handling identical to _fused_conv1x1_bn: the
    normalized output is bias-invariant; the bias folds only into the
    returned running-stat mean.  TPU-first fusion, no reference analog."""
    from .pallas_kernels import convkxk_bn_stats_train

    if has_bias:
        x, w, b, gamma, beta = arrays
    else:
        x, w, gamma, beta = arrays
        b = None
    z, mean, var = convkxk_bn_stats_train(x, w, tuple(pad))
    return _fused_bn_epilogue(z, mean, var, gamma, beta, b, eps, fix_gamma)


@register("_fused_conv1x1_bn_act", num_inputs=-1, num_outputs=-1)
def fused_conv1x1_bn_act(arrays, stride=(1, 1), eps=1e-5, fix_gamma=False,
                         has_bias=False, has_residual=False, relu=True):
    """The fused-EPILOGUE training op (round 9, ROADMAP item 2): 1x1
    NHWC conv + train-mode BatchNorm + optional residual-add + optional
    ReLU in ONE HBM pass over the conv output
    (ops/pallas_kernels.py matmul_stats + matmul_epilogue behind
    conv1x1_bn_act_train's custom_vjp).  Inputs
    ``[x, w, (bias), (residual), gamma, beta]`` — conv operands lead,
    BN affine trails (the AMP rule keeps the trailing pair fp32).
    Strided 1x1 pre-slices the input (exact).  A conv bias shifts z and
    the batch mean EQUALLY, so the normalized output is bias-invariant;
    the bias folds only into the returned mean (running statistics —
    hence inference — stay exact, same contract as _fused_conv1x1_bn).
    The residual adds BEFORE the relu — the ResNet bottleneck order
    ``relu(bn(conv(h)) + shortcut)``.  Returns
    ``(out, batch_mean, batch_var)``.  No reference analog — TPU-first
    fusion; the model-zoo BottleneckV1 routes here, see
    gluon/model_zoo/vision/resnet.py (MXNET_FUSED_EPILOGUE)."""
    from .pallas_kernels import conv1x1_bn_act_train

    x, w = arrays[0], arrays[1]
    idx = 2
    b = None
    if has_bias:
        b = arrays[idx]
        idx += 1
    r = None
    if has_residual:
        r = arrays[idx]
        idx += 1
    gamma, beta = arrays[idx], arrays[idx + 1]
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    out, mean, var = conv1x1_bn_act_train(
        x, w, gamma, beta, residual=r, eps=eps, relu=relu,
        fix_gamma=fix_gamma)
    if b is not None:
        mean = mean + b.astype(jnp.float32)
    return out, mean, var


@register("LayerNorm")
def layer_norm_op(data, gamma=None, beta=None, axis=-1, eps=1e-5):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


# register with 3 inputs
from .registry import get_op as _get_op  # noqa: E402

_get_op("LayerNorm").num_inputs = 3


@register("GroupNorm", num_inputs=-1)
def group_norm(arrays, num_groups=1, eps=1e-5):
    data, gamma, beta = arrays
    n, c = data.shape[0], data.shape[1]
    x = data.reshape((n, num_groups, c // num_groups) + data.shape[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = [1] * data.ndim
    shape[1] = c
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("InstanceNorm", num_inputs=-1)
def instance_norm(arrays, eps=1e-3):
    data, gamma, beta = arrays
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    x = (data - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[1] = data.shape[1]
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (axis 1)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    windows = sum(
        jax.lax.dynamic_slice_in_dim(padded, i, data.shape[1], axis=1)
        for i in range(nsize)
    )
    return data / jnp.power(knorm + alpha / nsize * windows, beta)


# --- dropout ---------------------------------------------------------------

@register("Dropout", num_inputs=2, rng_input=True)
def dropout(data, key, p=0.5, mode="training", axes=None, training=False,
            cudnn_off=None):
    """Reference src/operator/nn/dropout.cc.  ``key`` is a uint32 PRNG key
    array threaded explicitly so the op stays pure/traceable."""
    if not training and mode != "always":
        return data
    if p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask


# --- losses-as-ops ---------------------------------------------------------

@register("softmax_cross_entropy", num_inputs=2)
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=data.dtype)
    return -jnp.sum(onehot * logp)


@register("SoftmaxOutput", num_inputs=2, aliases=["Softmax"])
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    return jax.nn.softmax(data, axis=-1)


@register("MakeLoss", aliases=["make_loss"])
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return data


@register("CTCLoss", num_inputs=-1, aliases=["ctc_loss"])
def ctc_loss(arrays, use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """CTC loss via dynamic-programming in log space (reference
    src/operator/nn/ctc_loss.cc backed by warpctc; here a lax.scan DP)."""
    data = arrays[0]  # (seq, batch, alphabet)
    label = arrays[1]  # (batch, label_len)
    seq_len, batch, alphabet = data.shape
    blank = 0 if blank_label == "first" else alphabet - 1
    logp = jax.nn.log_softmax(data, axis=-1)

    lab = label.astype(jnp.int32)
    if blank_label == "first":
        lab = lab  # labels given 1-based? MXNet: labels are 0-based actual classes
    L = lab.shape[1]
    # extended label sequence with blanks: length 2L+1
    ext = jnp.full((batch, 2 * L + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = -1e30

    def init_alpha():
        a = jnp.full((batch, 2 * L + 1), neg_inf)
        a = a.at[:, 0].set(logp[0, :, blank])
        a = a.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])
        return a

    def step(alpha, lp):
        # lp: (batch, alphabet)
        emit = jnp.take_along_axis(lp, ext, axis=1)  # (batch, 2L+1)
        shift1 = jnp.concatenate([jnp.full((batch, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((batch, 2), neg_inf), alpha[:, :-2]], axis=1)
        same = ext == jnp.concatenate([jnp.full((batch, 2), blank), ext[:, :-2]], axis=1)
        cand = jnp.logaddexp(alpha, shift1)
        cand = jnp.where(same, cand, jnp.logaddexp(cand, shift2))
        new = cand + emit
        return new, None

    alpha0 = init_alpha()
    alpha, _ = jax.lax.scan(step, alpha0, logp[1:])
    ll = jnp.logaddexp(alpha[:, -1], alpha[:, -2])
    return -ll


# --- upsampling / misc -----------------------------------------------------

@register("UpSampling", num_inputs=-1)
def upsampling(arrays, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=None):
    data = arrays[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        return out
    # bilinear
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


@register("moments", num_outputs=-1)
def moments(data, axes=None, keepdims=False):
    mean = jnp.mean(data, axis=tuple(axes) if axes else None, keepdims=keepdims)
    var = jnp.var(data, axis=tuple(axes) if axes else None, keepdims=keepdims)
    return (mean, var)
