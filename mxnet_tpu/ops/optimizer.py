"""Optimizer update operators.

Reference: ``src/operator/optimizer_op.cc`` — updates run as device-side ops
(sgd_update:*, adam_update:649, lamb_phase1/2:917, multi_sgd:313).  Same
design here: each update is one fused XLA computation; multi-tensor variants
take flat lists so XLA emits a single program over all params.

These ops are *mutating* at the NDArray layer (weight is rewritten); the
registry fns stay pure — the python optimizer wrapper writes results back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _apply_wd(grad, weight, wd, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


def _absent_rows_keep(weight, grad, new_w):
    """lazy_update semantics (reference optimizer_op.cc row_sparse sgd):
    rows absent from the gradient — all-zero rows in the dense lowering
    of a row_sparse grad — keep their weights EXACTLY (no wd decay)."""
    present = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))
    shape = (-1,) + (1,) * (weight.ndim - 1)
    return jnp.where(present.reshape(shape), new_w, weight)


@register("sgd_update", num_inputs=2, num_outputs=1, differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_w = weight - lr * g
    if lazy_update and grad.ndim >= 1:
        return _absent_rows_keep(weight, grad, new_w)
    return new_w


@register("sgd_mom_update", num_inputs=3, num_outputs=-1, differentiable=False)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    if lazy_update and grad.ndim >= 1:
        # absent rows: weight AND momentum untouched (reference rsp sgd)
        present = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))
        shape = (-1,) + (1,) * (weight.ndim - 1)
        p = present.reshape(shape)
        new_mom = jnp.where(p, momentum * mom - lr * g, mom)
        return (jnp.where(p, weight + new_mom, weight), new_mom)
    new_mom = momentum * mom - lr * g
    return (weight + new_mom, new_mom)


@register("nag_mom_update", num_inputs=3, num_outputs=-1, differentiable=False)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    return (weight - lr * (g + momentum * new_mom), new_mom)


@register("adam_update", num_inputs=4, num_outputs=-1, differentiable=False)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    out = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return (out, new_mean, new_var)


@register("adamw_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def adamw_update(arrays, lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 wd=0.0, eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
    weight, grad, mean, var = arrays[:4]
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    out = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight)
    return (out, new_mean, new_var)


@register("rmsprop_update", num_inputs=3, num_outputs=-1, differentiable=False)
def rmsprop_update(weight, grad, n, lr=0.001, rho=0.9, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    out = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        out = jnp.clip(out, -clip_weights, clip_weights)
    return (out, new_n)


@register("rmspropalex_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def rmspropalex_update(arrays, lr=0.001, rho=0.95, momentum=0.9, epsilon=1e-8,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       clip_weights=-1.0):
    weight, grad, n, g_acc, delta = arrays
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    new_g = rho * g_acc + (1 - rho) * g
    new_delta = momentum * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    out = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        out = jnp.clip(out, -clip_weights, clip_weights)
    return (out, new_n, new_g, new_delta)


@register("ftrl_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def ftrl_update(arrays, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    weight, grad, z, n = arrays
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    out = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return (out, new_z, new_n)


@register("signsgd_update", num_inputs=2, differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_inputs=3, num_outputs=-1, differentiable=False)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    out = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom) - lr * wd * weight
    return (out, new_mom)


@register("adagrad_update", num_inputs=3, num_outputs=-1, differentiable=False,
          aliases=["_sparse_adagrad_update"])
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_hist = history + jnp.square(g)
    return (weight - lr * g / (jnp.sqrt(new_hist) + epsilon), new_hist)


@register("adadelta_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def adadelta_update(arrays, rho=0.9, epsilon=1e-5, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    weight, grad, acc_g, acc_delta = arrays
    g = _apply_wd(grad, weight, wd, rescale_grad, clip_gradient)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return (weight - delta, new_acc_g, new_acc_delta)


# --- LAMB (reference optimizer_op.cc lamb_phase1/2 + contrib multi_lamb) ---

@register("lamb_update_phase1", num_inputs=4, num_outputs=-1, differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m = new_mean
    v = new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight
    return (update, new_mean, new_var)


@register("lamb_update_phase2", num_inputs=-1, differentiable=False)
def lamb_update_phase2(arrays, lr=0.01, lower_bound=-1.0, upper_bound=-1.0):
    weight, g_update, r1, r2 = arrays
    r1 = jnp.where(r1 > 0, r1, jnp.ones_like(r1))
    r2 = jnp.where(r2 > 0, r2, jnp.ones_like(r2))
    ratio = r1 / r2
    if lower_bound is not None and lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    return weight - lr * ratio * g_update


# --- multi-tensor fused updates (reference contrib multi_* / preloaded_*) --
#
# INPUT LAYOUT: the reference lists multi-tensor inputs INTERLEAVED per
# weight — weight_0, grad_0, (mom_0/mean_0/..,) weight_1, grad_1, ... —
# see optimizer_op.cc:321 (multi_sgd FListInputNames),
# preloaded_multi_sgd.cc:55, multi_lamb.cc:186 (LAMBParamToVector), and
# adamw.cc:177.  These ops follow that convention exactly so call sites
# written against the reference keep working.  OUTPUT layout is blocked by
# kind (all new weights, then all new aux states): the reference mutates
# aux states in place and only *returns* weights, so there is no reference
# output convention for the aux arrays — blocked is this framework's
# functional-update convention.


def _interleaved(arrays, kinds, num_weights=0, trailing=0):
    """Split the reference's interleaved multi-tensor input layout into
    per-kind tuples; ``trailing`` arrays (e.g. lrs, wds) follow the body."""
    body_len = len(arrays) - trailing
    n = num_weights or body_len // kinds
    if body_len != n * kinds:
        raise ValueError(
            f"multi-tensor op expects {kinds} interleaved arrays per weight"
            f" (+{trailing} trailing); got {len(arrays)} arrays for"
            f" num_weights={n}")
    groups = tuple(tuple(arrays[i * kinds + k] for i in range(n))
                   for k in range(kinds))
    return n, groups, tuple(arrays[body_len:])


@register("multi_sgd_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def multi_sgd_update(arrays, lrs=(), wds=(), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, w1, g1, ...] (interleaved; reference
    optimizer_op.cc:321) -> (new_w0, new_w1, ...)."""
    n, (weights, grads), _ = _interleaved(arrays, 2, num_weights)
    outs = []
    for w, g, lr, wd in zip(weights, grads, lrs, wds):
        gg = _apply_wd(g, w, wd, rescale_grad, clip_gradient)
        outs.append(w - lr * gg)
    return tuple(outs)


@register("multi_sgd_mom_update", num_inputs=-1, num_outputs=-1, differentiable=False)
def multi_sgd_mom_update(arrays, lrs=(), wds=(), momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, m0, w1, g1, m1, ...] (interleaved) ->
    (new_w..., new_m...)."""
    n, (weights, grads, moms), _ = _interleaved(arrays, 3, num_weights)
    outs = []
    for w, g, m, lr, wd in zip(weights, grads, moms, lrs, wds):
        gg = _apply_wd(g, w, wd, rescale_grad, clip_gradient)
        nm = momentum * m - lr * gg
        outs.append((w + nm, nm))
    ws = tuple(o[0] for o in outs)
    ms = tuple(o[1] for o in outs)
    return ws + ms


@register("multi_sum_sq", num_inputs=-1, num_outputs=1, differentiable=False)
def multi_sum_sq(arrays, num_arrays=0):
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays])


@register("multi_lamb_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def multi_lamb_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                      beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                      lower_bound=-1.0, upper_bound=-1.0, clip_gradient=-1.0,
                      bias_correction=True, step_count=(), num_tensors=0):
    """Fused multi-tensor LAMB (reference contrib/multi_lamb.cc:186): arrays
    = [w0, g0, m0, v0, w1, ...] (interleaved) ->
    (new_w..., new_m..., new_v...)."""
    n, (ws, gs, ms, vs), _ = _interleaved(arrays, 4, num_tensors)
    new_w, new_m, new_v = [], [], []
    for i, (w, g, m, v) in enumerate(zip(ws, gs, ms, vs)):
        t = step_count[i] if i < len(step_count) else 1
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        m_n = beta1 * m + (1 - beta1) * g
        v_n = beta2 * v + (1 - beta2) * jnp.square(g)
        mh, vh = m_n, v_n
        if bias_correction:
            mh = m_n / (1 - beta1 ** t)
            vh = v_n / (1 - beta2 ** t)
        wf = w.astype(jnp.float32)
        upd = mh / (jnp.sqrt(vh) + epsilon) + wds[i] * wf
        r1 = jnp.linalg.norm(wf)
        if lower_bound is not None and lower_bound > 0:
            r1 = jnp.maximum(r1, lower_bound)
        if upper_bound is not None and upper_bound > 0:
            r1 = jnp.minimum(r1, upper_bound)
        r2 = jnp.linalg.norm(upd)
        ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
        new_w.append((wf - learning_rates[i] * ratio * upd).astype(w.dtype))
        new_m.append(m_n)
        new_v.append(v_n)
    return tuple(new_w) + tuple(new_m) + tuple(new_v)


@register("multi_lans_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def multi_lans_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                      beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                      lower_bound=-1.0, upper_bound=-1.0, clip_gradient=-1.0,
                      step_count=(), num_tensors=0):
    """Fused multi-tensor LANS (reference contrib/multi_lans.cc:38-120):
    per tensor, the gradient is L2-normalised before the Adam moments, and
    the update blends a momentum direction and a gradient direction, each
    with its own trust ratio:

        sg   = (g * rescale) / ||g||          (then optional clip)
        m,v  = adam moments of sg (bias-corrected)
        d_m  = m_hat / (sqrt(v_hat)+eps) + wd*w
        d_g  = sg    / (sqrt(v_hat)+eps) + wd*w
        w   -= lr * (beta1 * (||w||/||d_m||) * d_m
                     + (1-beta1) * (||w||/||d_g||) * d_g)

    arrays = [w0, g0, m0, v0, w1, ...] (interleaved) ->
    (new_w..., new_m..., new_v...).
    """
    n, (ws, gs, ms, vs), _ = _interleaved(arrays, 4, num_tensors)
    new_w, new_m, new_v = [], [], []
    for i, (w, g, m, v) in enumerate(zip(ws, gs, ms, vs)):
        t = step_count[i] if i < len(step_count) else 1
        gf = g.astype(jnp.float32) * rescale_grad
        gnorm = jnp.linalg.norm(gf)
        sg = gf / jnp.maximum(gnorm, 1e-12)
        if clip_gradient is not None and clip_gradient >= 0:
            sg = jnp.clip(sg, -clip_gradient, clip_gradient)
        m_n = beta1 * m + (1 - beta1) * sg
        v_n = beta2 * v + (1 - beta2) * jnp.square(sg)
        mh = m_n / (1 - beta1 ** t)
        vh = jnp.sqrt(v_n / (1 - beta2 ** t)) + epsilon
        wf = w.astype(jnp.float32)
        d_m = mh / vh + wds[i] * wf
        d_g = sg / vh + wds[i] * wf
        r1 = jnp.linalg.norm(wf)
        if lower_bound is not None and lower_bound > 0:
            r1 = jnp.maximum(r1, lower_bound)
        if upper_bound is not None and upper_bound > 0:
            r1 = jnp.minimum(r1, upper_bound)
        rm = jnp.linalg.norm(d_m)
        rg = jnp.linalg.norm(d_g)
        ratio_m = jnp.where((r1 > 0) & (rm > 0), r1 / rm, 1.0)
        ratio_g = jnp.where((r1 > 0) & (rg > 0), r1 / rg, 1.0)
        upd = beta1 * ratio_m * d_m + (1 - beta1) * ratio_g * d_g
        new_w.append((wf - learning_rates[i] * upd).astype(w.dtype))
        new_m.append(m_n)
        new_v.append(v_n)
    return tuple(new_w) + tuple(new_m) + tuple(new_v)


# --- mixed-precision master-weight variants (reference optimizer_op.cc
# mp_* registrations: fp16/bf16 weights with an fp32 master copy; the
# update runs in fp32 and both copies are returned) ----------------------

def _mp(update_fn, weight, weight32, *states, **kw):
    out = update_fn(weight32, *states, **kw)
    outs = out if isinstance(out, tuple) else (out,)
    new_w32 = outs[0]
    return (new_w32.astype(weight.dtype), new_w32) + outs[1:]


@register("mp_sgd_update", num_inputs=3, num_outputs=-1, differentiable=False)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=False):
    """SGD on the fp32 master weight (reference mp_sgd_update); returns
    (weight_cast, weight32)."""
    return _mp(sgd_update, weight, weight32, grad.astype(jnp.float32),
               lr=lr, wd=wd, rescale_grad=rescale_grad,
               clip_gradient=clip_gradient)


@register("mp_sgd_mom_update", num_inputs=4, num_outputs=-1,
          differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=False):
    new_w32, new_mom = sgd_mom_update(
        weight32, grad.astype(jnp.float32), mom, lr=lr, momentum=momentum,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return (new_w32.astype(weight.dtype), new_mom, new_w32)


@register("mp_nag_mom_update", num_inputs=4, num_outputs=-1,
          differentiable=False)
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    new_w32, new_mom = nag_mom_update(
        weight32, grad.astype(jnp.float32), mom, lr=lr, momentum=momentum,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return (new_w32.astype(weight.dtype), new_mom, new_w32)


@register("mp_lamb_update_phase1", num_inputs=5, num_outputs=-1,
          differentiable=False)
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1, wd=0.0,
                          bias_correction=True, rescale_grad=1.0,
                          clip_gradient=-1.0):
    """LAMB phase 1 against the fp32 master weight (reference
    mp_lamb_update_phase1; the 5-input form passes weight32 last)."""
    w = weight32 if weight32 is not None else weight.astype(jnp.float32)
    return lamb_update_phase1(
        w, grad.astype(jnp.float32), mean, var, beta1=beta1, beta2=beta2,
        epsilon=epsilon, t=t, wd=wd, bias_correction=bias_correction,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)


@register("mp_lamb_update_phase2", num_inputs=-1, num_outputs=1,
          differentiable=False)
def mp_lamb_update_phase2(arrays, lr=0.01, lower_bound=-1.0,
                          upper_bound=-1.0):
    """(weight, g_update, r1, r2, weight32) -> fp16 weight; the fp32 master
    is updated and narrowed (reference mp_lamb_update_phase2)."""
    weight, g_update, r1, r2, weight32 = arrays
    new_w32 = lamb_update_phase2([weight32, g_update, r1, r2], lr=lr,
                                 lower_bound=lower_bound,
                                 upper_bound=upper_bound)
    return new_w32.astype(weight.dtype)


@register("ftml_update", num_inputs=5, num_outputs=-1, differentiable=False)
def ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    """FTML — Follow The Moving Leader (reference optimizer_op-inl.h:1159
    FTMLKernel): returns (weight, d, v, z)."""
    g = grad * rescale_grad
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    g = g + wd * weight
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t))
                                   + epsilon)
    new_z = beta1 * z + (1 - beta1) * g - (d_t - beta1 * d) * weight
    new_d = d_t
    return (-new_z / d_t, new_d, new_v, new_z)


@register("multi_lars", num_inputs=4, num_outputs=1, differentiable=False)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001, eps=1e-8,
               rescale_grad=1.0):
    """Vectorized LARS coefficients from per-tensor squared norms
    (reference contrib/multi_lars-inl.h:61 MultiLARSKernel)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq)
    valid = (w_norm > 0) & (grads_sum_sq > 0)
    scaled = lrs * eta * w_norm / (g_norm * rescale_grad + wds * w_norm
                                   + eps)
    return jnp.where(valid, scaled, lrs)


@register("group_adagrad_update", num_inputs=3, num_outputs=-1,
          differentiable=False, aliases=("_contrib_group_adagrad_update",))
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Per-row (grouped) AdaGrad for embedding tables (reference
    contrib/optimizer_op-inl.h:99): history accumulates the per-row MEAN
    squared gradient; returns (weight, history)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    row_mean_sq = jnp.mean(jnp.square(g), axis=tuple(range(1, g.ndim)))
    new_hist = history + row_mean_sq
    denom = jnp.sqrt(new_hist) + epsilon
    shape = (-1,) + (1,) * (g.ndim - 1)
    return (weight - lr * g / denom.reshape(shape), new_hist)


# --- preloaded multi-tensor SGD: lrs/wds arrive as device arrays instead
# of attrs, so LR schedules never force a re-trace (reference
# contrib/preloaded_multi_sgd.cc) ---------------------------------------

@register("preloaded_multi_sgd_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def preloaded_multi_sgd_update(arrays, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=0):
    """arrays = [w0, g0, w1, g1, ..., lrs, wds] (interleaved; reference
    preloaded_multi_sgd.cc:55)."""
    n, (ws, gs), (lrs, wds) = _interleaved(arrays, 2, num_weights,
                                           trailing=2)
    outs = []
    for i, (w, g) in enumerate(zip(ws, gs)):
        gg = _apply_wd(g, w, wds[i], rescale_grad, clip_gradient)
        outs.append(w - lrs[i] * gg)
    return tuple(outs)


@register("preloaded_multi_sgd_mom_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def preloaded_multi_sgd_mom_update(arrays, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, m0, w1, ..., lrs, wds] (interleaved; reference
    preloaded_multi_sgd.cc:104)."""
    n, (ws, gs, ms), (lrs, wds) = _interleaved(arrays, 3, num_weights,
                                               trailing=2)
    new_w, new_m = [], []
    for i, (w, g, m) in enumerate(zip(ws, gs, ms)):
        gg = _apply_wd(g, w, wds[i], rescale_grad, clip_gradient)
        nm = momentum * m - lrs[i] * gg
        new_w.append(w + nm)
        new_m.append(nm)
    return tuple(new_w) + tuple(new_m)


@register("preloaded_multi_mp_sgd_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def preloaded_multi_mp_sgd_update(arrays, rescale_grad=1.0,
                                  clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, w32_0, w1, ..., lrs, wds] (interleaved; reference
    preloaded_multi_sgd.cc:153) -> (w..., w32...)."""
    n, (ws, gs, w32s), (lrs, wds) = _interleaved(arrays, 3, num_weights,
                                                 trailing=2)
    new_w, new_w32 = [], []
    for i, (w, g, w32) in enumerate(zip(ws, gs, w32s)):
        gg = _apply_wd(g.astype(jnp.float32), w32, wds[i], rescale_grad,
                       clip_gradient)
        nw32 = w32 - lrs[i] * gg
        new_w.append(nw32.astype(w.dtype))
        new_w32.append(nw32)
    return tuple(new_w) + tuple(new_w32)


@register("preloaded_multi_mp_sgd_mom_update", num_inputs=-1,
          num_outputs=-1, differentiable=False)
def preloaded_multi_mp_sgd_mom_update(arrays, momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, m0, w32_0, w1, ..., lrs, wds] (interleaved;
    reference preloaded_multi_sgd.cc:190)."""
    n, (ws, gs, ms, w32s), (lrs, wds) = _interleaved(arrays, 4, num_weights,
                                                     trailing=2)
    new_w, new_m, new_w32 = [], [], []
    for i, (w, g, m, w32) in enumerate(zip(ws, gs, ms, w32s)):
        gg = _apply_wd(g.astype(jnp.float32), w32, wds[i], rescale_grad,
                       clip_gradient)
        nm = momentum * m - lrs[i] * gg
        nw32 = w32 + nm
        new_w.append(nw32.astype(w.dtype))
        new_m.append(nm)
        new_w32.append(nw32)
    return tuple(new_w) + tuple(new_m) + tuple(new_w32)


@register("multi_mp_sgd_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def multi_mp_sgd_update(arrays, lrs=(), wds=(), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, w32_0, w1, ...] (interleaved; reference
    optimizer_op.cc multi_mp_sgd) -> (w..., w32...)."""
    n, (ws, gs, w32s), _ = _interleaved(arrays, 3, num_weights)
    new_w, new_w32 = [], []
    for w, g, w32, lr, wd in zip(ws, gs, w32s, lrs, wds):
        gg = _apply_wd(g.astype(jnp.float32), w32, wd, rescale_grad,
                       clip_gradient)
        nw32 = w32 - lr * gg
        new_w.append(nw32.astype(w.dtype))
        new_w32.append(nw32)
    return tuple(new_w) + tuple(new_w32)


@register("multi_mp_sgd_mom_update", num_inputs=-1, num_outputs=-1,
          differentiable=False)
def multi_mp_sgd_mom_update(arrays, lrs=(), wds=(), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=0):
    """arrays = [w0, g0, m0, w32_0, w1, ...] (interleaved; reference
    optimizer_op.cc multi_mp_sgd_mom FListInputNames)."""
    n, (ws, gs, ms, w32s), _ = _interleaved(arrays, 4, num_weights)
    new_w, new_m, new_w32 = [], [], []
    for w, g, m, w32, lr, wd in zip(ws, gs, ms, w32s, lrs, wds):
        gg = _apply_wd(g.astype(jnp.float32), w32, wd, rescale_grad,
                       clip_gradient)
        nm = momentum * m - lr * gg
        nw32 = w32 + nm
        new_w.append(nw32.astype(w.dtype))
        new_m.append(nm)
        new_w32.append(nw32)
    return tuple(new_w) + tuple(new_m) + tuple(new_w32)


@register("mp_adamw_update", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_mp_adamw_update",))
def mp_adamw_update(arrays, lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                    wd=0.0, eta=1.0, rescale_grad=1.0, clip_gradient=-1.0):
    """AdamW against the fp32 master weight (reference _mp_adamw_update):
    arrays = [weight, grad, mean, var, weight32] ->
    (weight_cast, mean, var, weight32)."""
    weight, grad, mean, var, weight32 = arrays[:5]
    new_w32, new_mean, new_var = adamw_update(
        [weight32, grad.astype(jnp.float32), mean, var], lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return (new_w32.astype(weight.dtype), new_mean, new_var, new_w32)


@register("multi_adamw_update", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_multi_adamw_update",))
def multi_adamw_update(arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                       beta2=0.999, epsilon=1e-8, rescale_grad=1.0,
                       clip_gradient=-1.0, num_weights=0):
    """Fused list-AdamW (reference contrib/adamw.cc:168 multi variant):
    arrays = [w0, g0, m0, v0, w1, ...] (interleaved), optionally followed
    by ONE trailing rescale_grad scalar tensor (the reference takes
    num_weights*4 + 1 inputs) -> (w..., m..., v...)."""
    trailing = 1 if (len(arrays) - (num_weights or 0) * 4 == 1
                     or (not num_weights and len(arrays) % 4 == 1)) else 0
    n, (ws, gs, ms, vs), rest = _interleaved(arrays, 4, num_weights,
                                             trailing=trailing)
    if rest:
        rescale_grad = rest[0]
    new_w, new_m, new_v = [], [], []
    for i, (w, g, m, v) in enumerate(zip(ws, gs, ms, vs)):
        eta = etas[i] if i < len(etas) else 1.0
        lr = lrs[i] if i < len(lrs) else 0.001
        wd = wds[i] if i < len(wds) else 0.0
        nw, nm, nv = adamw_update(
            [w, g, m, v], lr=lr, beta1=beta1, beta2=beta2,
            epsilon=epsilon, wd=wd, eta=eta, rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        new_w.append(nw)
        new_m.append(nm)
        new_v.append(nv)
    return tuple(new_w) + tuple(new_m) + tuple(new_v)


@register("multi_mp_adamw_update", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_multi_mp_adamw_update",))
def multi_mp_adamw_update(arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                          beta2=0.999, epsilon=1e-8, rescale_grad=1.0,
                          clip_gradient=-1.0, num_weights=0):
    """arrays = [w0, g0, m0, v0, w32_0, w1, ...] (interleaved; reference
    adamw.cc:224), optionally + ONE trailing rescale_grad tensor ->
    (w..., m..., v..., w32...)."""
    trailing = 1 if (len(arrays) - (num_weights or 0) * 5 == 1
                     or (not num_weights and len(arrays) % 5 == 1)) else 0
    n, (ws, gs, ms, vs, w32s), rest = _interleaved(arrays, 5, num_weights,
                                                   trailing=trailing)
    if rest:
        rescale_grad = rest[0]
    new_w, new_m, new_v, new_w32 = [], [], [], []
    for i, (w, g, m, v, w32) in enumerate(zip(ws, gs, ms, vs, w32s)):
        eta = etas[i] if i < len(etas) else 1.0
        lr = lrs[i] if i < len(lrs) else 0.001
        wd = wds[i] if i < len(wds) else 0.0
        nw32, nm, nv = adamw_update(
            [w32, g.astype(jnp.float32), m, v], lr=lr, beta1=beta1,
            beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        new_w.append(nw32.astype(w.dtype))
        new_m.append(nm)
        new_v.append(nv)
        new_w32.append(nw32)
    return tuple(new_w) + tuple(new_m) + tuple(new_v) + tuple(new_w32)


@register("multi_mp_lamb_update", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_multi_mp_lamb_update",))
def multi_mp_lamb_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                         beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                         lower_bound=-1.0, upper_bound=-1.0,
                         clip_gradient=-1.0, bias_correction=True,
                         step_count=(), num_tensors=0):
    """Master-weight multi-LAMB: arrays = [w0, g0, m0, v0, w32_0, w1, ...]
    (interleaved; reference multi_lamb.cc:224 mp variant) ->
    (w..., m..., v..., w32...)."""
    n, (ws, gs, ms, vs, w32s), _ = _interleaved(arrays, 5, num_tensors)
    inner = []
    for w32, g, m, v in zip(w32s, gs, ms, vs):
        inner += [w32, g.astype(jnp.float32), m, v]
    packed = multi_lamb_update(
        inner,
        learning_rates=learning_rates, wds=wds, beta1=beta1, beta2=beta2,
        epsilon=epsilon, rescale_grad=rescale_grad, lower_bound=lower_bound,
        upper_bound=upper_bound, clip_gradient=clip_gradient,
        bias_correction=bias_correction, step_count=step_count,
        num_tensors=n)
    nw32, nm, nv = packed[:n], packed[n:2 * n], packed[2 * n:3 * n]
    casts = tuple(w32.astype(w.dtype) for w, w32 in zip(ws, nw32))
    return casts + tuple(nm) + tuple(nv) + tuple(nw32)


@register("multi_mp_lans_update", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_multi_mp_lans_update",))
def multi_mp_lans_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                         beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                         lower_bound=-1.0, upper_bound=-1.0,
                         clip_gradient=-1.0, step_count=(), num_tensors=0):
    """Master-weight multi-LANS, same interleaved layout as
    multi_mp_lamb_update."""
    n, (ws, gs, ms, vs, w32s), _ = _interleaved(arrays, 5, num_tensors)
    inner = []
    for w32, g, m, v in zip(w32s, gs, ms, vs):
        inner += [w32, g.astype(jnp.float32), m, v]
    packed = multi_lans_update(
        inner,
        learning_rates=learning_rates, wds=wds, beta1=beta1, beta2=beta2,
        epsilon=epsilon, rescale_grad=rescale_grad, lower_bound=lower_bound,
        upper_bound=upper_bound, clip_gradient=clip_gradient,
        step_count=step_count, num_tensors=n)
    nw32, nm, nv = packed[:n], packed[n:2 * n], packed[2 * n:3 * n]
    casts = tuple(w32.astype(w.dtype) for w, w32 in zip(ws, nw32))
    return casts + tuple(nm) + tuple(nv) + tuple(nw32)
