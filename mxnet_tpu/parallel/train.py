"""Sharded training step: the pjit analog of Trainer.step + KVStore.

The reference's training loop splits across Trainer._allreduce_grads
(gluon/trainer.py:385 → KVStore pushpull → Comm*/NCCL/ps-lite) and
device-side optimizer ops (optimizer_op.cc).  TPU-native, the WHOLE step —
forward, backward, gradient all-reduce over the ``dp`` mesh axis, and the
fused optimizer update — is ONE jitted SPMD program: parameters carry
``NamedSharding``s from a ``ShardingPlan``, the batch is sharded over the
data axes, and XLA inserts the gradient all-reduce (the kvstore='tpu'
collective) plus any tp/ep/pp collectives the plan implies.  Buffer donation
on (params, opt_state) gives in-place update semantics (the reference's
kWriteInplace/static_alloc story) for free.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import autograd
from .. import random as _random
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap
from .sharding import ShardingPlan, constraint as _sh_constraint, \
    replicated_plan

__all__ = ["functional_call", "ShardedTrainer"]


def functional_call(block, param_arrays: Dict[str, jax.Array], args: Sequence,
                    *, training: bool = True, rng_key=None):
    """Run ``block.forward`` as a pure function of ``param_arrays``.

    Temporarily installs the given jax arrays into the block's Parameters
    (every ctx replica, so tracing is replica-agnostic), traces forward, and
    restores.  Returns ``(outputs, {mutated param name: new array})`` —
    mutations (BatchNorm running stats) are detected by Parameter version
    bumps, the same trick HybridBlock's whole-graph jit uses
    (gluon/block.py _build_cache).
    """
    params = block.collect_params()
    installed = []
    for n, arr in param_arrays.items():
        p = params[n]
        for d in p._data:
            installed.append((n, d, d._data, d._version))
            d._data = arr
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    _random.push_trace_key(rng_key)
    prev_rec = autograd.set_recording(False)
    prev_train = autograd.set_training(training)
    try:
        ctx = current_context()
        nd_args = [
            _wrap(a, ctx) if not isinstance(a, NDArray) else a for a in args
        ]
        out = block.forward(*nd_args)
    finally:
        autograd.set_recording(prev_rec)
        autograd.set_training(prev_train)
        _random.pop_trace_key()
        mutated: Dict[str, jax.Array] = {}
        for n, d, old, ver in installed:
            if d._version != ver and n not in mutated:
                mutated[n] = d._data
            d._data = old
            d._version = ver
    return out, mutated


def _bias_corrected_lr(lr, beta1, beta2, t):
    return lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)


class ShardedTrainer:
    """End-to-end sharded train step for an initialized (Hybrid)Block.

    ``loss_fn(outputs, label_ndarray) -> scalar NDArray`` runs inside the
    trace (gluon Loss blocks work directly).  ``batch_spec``/``label_spec``
    default to sharding dim 0 over every data axis present in the mesh.
    """

    def __init__(self, block, loss_fn: Callable, mesh: Mesh,
                 plan: Optional[ShardingPlan] = None, optimizer: str = "sgd",
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 batch_spec: Optional[P] = None,
                 label_spec: Optional[P] = None,
                 donate: bool = True, grad_accum: int = 1,
                 compute_dtype=None, remat: Optional[bool] = None):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh
        # recompute-in-backward (jax.checkpoint over the whole forward) —
        # the reference mirror path; lets batch/sequence scale past HBM at
        # ~1 extra forward of FLOPs.  None = follow the documented
        # MXNET_BACKWARD_DO_MIRROR global default.
        if remat is None:
            from .. import config as _config

            remat = bool(_config.get("MXNET_BACKWARD_DO_MIRROR"))
        self.remat = bool(remat)
        # mixed precision: params/optimizer state stay fp32 (master
        # weights); fwd+bwd compute casts to ``compute_dtype`` (bf16 puts
        # the matmuls on the MXU's native path), grads flow back fp32
        # through the cast, loss reduces in fp32
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.plan = plan if plan is not None else replicated_plan()
        self.opt = optimizer.lower()
        kw = dict(optimizer_params or {})
        self.lr = float(kw.pop("learning_rate", kw.pop("lr", 0.01)))
        self.momentum = float(kw.pop("momentum", 0.0))
        self.wd = float(kw.pop("wd", 0.0))
        self.beta1 = float(kw.pop("beta1", 0.9))
        self.beta2 = float(kw.pop("beta2", 0.999))
        self.epsilon = float(kw.pop("epsilon", 1e-8))
        if kw:
            raise ValueError(
                f"unsupported optimizer_params for ShardedTrainer: {list(kw)}")
        self.donate = donate

        params = block.collect_params()
        uninit = [n for n, p in params.items() if p._data is None]
        if uninit:
            raise ValueError(
                f"initialize() the block before ShardedTrainer: {uninit[:3]}")
        self.names: List[str] = list(params)
        # grad_req='add' (the reference's kAddTo accumulate-into-grad, used
        # for micro-batch accumulation) maps onto in-step accumulation: the
        # scan over grad_accum micro-batches sums each param's gradient
        # before the single optimizer update, so 'add' params are simply
        # trainable here
        self.grad_names = [n for n in self.names
                           if params[n].grad_req != "null"]
        self.grad_accum = int(grad_accum)
        assert self.grad_accum >= 1
        # copy before sharding: device_put may alias the source buffer for
        # the co-located shard, and step donation would delete the
        # Parameter's own array through that alias
        arrays = {n: jnp.array(params[n]._data[0]._data, copy=True)
                  for n in self.names}
        self.params: Dict[str, jax.Array] = self.plan.shard_tree(arrays, mesh)
        self.opt_state = self._init_opt_state()

        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.shape
                          and mesh.shape[a] > 1)
        default_spec = P(data_axes if data_axes else None)
        self.batch_spec = batch_spec if batch_spec is not None else default_spec
        self.label_spec = label_spec if label_spec is not None else default_spec
        self.step_count = 0
        self._jitted: Dict[Any, Callable] = {}

    # -- optimizer -------------------------------------------------------
    def _init_opt_state(self) -> Dict[str, Tuple[jax.Array, ...]]:
        def like(n):
            w = self.params[n]
            z = jnp.zeros(w.shape, dtype=w.dtype)
            return jax.device_put(z, w.sharding)

        state = {}
        for n in self.grad_names:
            if self.opt == "sgd":
                state[n] = (like(n),) if self.momentum else ()
            elif self.opt in ("adam", "adamw", "lamb"):
                state[n] = (like(n), like(n))
            else:
                raise ValueError(f"unsupported sharded optimizer {self.opt}")
        return state

    def _apply_update(self, name, w, g, state, t):
        from ..ops import optimizer as opt_ops

        lr, wd = self.lr, self.wd
        if self.opt == "sgd":
            if self.momentum:
                new_w, new_m = opt_ops.sgd_mom_update(
                    w, g, state[0], lr=lr, momentum=self.momentum, wd=wd)
                return new_w, (new_m,)
            return opt_ops.sgd_update(w, g, lr=lr, wd=wd), ()
        if self.opt == "adam":
            lr_t = _bias_corrected_lr(lr, self.beta1, self.beta2, t)
            new_w, m, v = opt_ops.adam_update(
                w, g, state[0], state[1], lr=lr_t, beta1=self.beta1,
                beta2=self.beta2, epsilon=self.epsilon, wd=wd)
            return new_w, (m, v)
        if self.opt == "adamw":
            # bias-corrected lr, matching optimizer/adam.py correct_bias=True
            lr_t = _bias_corrected_lr(lr, self.beta1, self.beta2, t)
            new_w, m, v = opt_ops.adamw_update(
                [w, g, state[0], state[1]], lr=lr_t, beta1=self.beta1,
                beta2=self.beta2, epsilon=self.epsilon, wd=wd)
            return new_w, (m, v)
        if self.opt == "lamb":
            gdir, m, v = opt_ops.lamb_update_phase1(
                w, g, state[0], state[1], beta1=self.beta1, beta2=self.beta2,
                epsilon=self.epsilon, t=t, wd=wd)
            r1 = jnp.linalg.norm(w.astype(jnp.float32))
            r2 = jnp.linalg.norm(gdir.astype(jnp.float32))
            new_w = opt_ops.lamb_update_phase2([w, gdir, r1, r2], lr=lr)
            return new_w, (m, v)
        raise ValueError(self.opt)

    # -- the step --------------------------------------------------------
    def _build(self):
        # (jit itself re-specializes by shape; the _jitted cache keyed on the
        # input signature only avoids re-wrapping)
        block, loss_fn = self.block, self.loss_fn
        names, grad_names = self.names, self.grad_names
        frozen = [n for n in names if n not in grad_names]

        accum = self.grad_accum

        cd = self.compute_dtype

        def step_fn(params, opt_state, data, label, key, t):
            def loss_of(trainable, data, label, key, overrides=None):
                all_p = dict(trainable)
                for n in frozen:
                    all_p[n] = params[n]
                if overrides:
                    # chained running stats from earlier micro-batches
                    # (only frozen params — BN stats — are overridden)
                    for n, arr in overrides.items():
                        if n not in grad_names:
                            all_p[n] = arr
                if cd is not None:
                    all_p = {n: (a.astype(cd)
                                 if jnp.issubdtype(a.dtype, jnp.floating)
                                 else a) for n, a in all_p.items()}
                    if jnp.issubdtype(data.dtype, jnp.floating):
                        data = data.astype(cd)
                out, mutated = functional_call(
                    block, all_p, (data,), training=True, rng_key=key)
                label_nd = _wrap(label, current_context())
                loss = loss_fn(out, label_nd)
                if isinstance(loss, NDArray):
                    loss = loss._data
                loss = jnp.mean(loss).astype(jnp.float32)
                return loss, mutated

            if self.remat:
                loss_of = jax.checkpoint(loss_of, static_argnums=())

            trainable = {n: params[n] for n in grad_names}
            if accum == 1:
                (loss, mutated), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(trainable, data, label, key)
            else:
                # micro-batch gradient accumulation inside the one jitted
                # step (the reference's kAddTo/grad_req='add' story): scan
                # over accum micro-batches, sum grads, average at the end.
                # For per-sample-mean losses and equal micro-batches this
                # matches the full-batch gradient exactly.
                def to_micro(x, spec):
                    x = x.reshape((accum, x.shape[0] // accum)
                                  + x.shape[1:])
                    return _sh_constraint(x, P(None, *spec))

                data_m = to_micro(data, self.batch_spec)
                label_m = to_micro(label, self.label_spec)
                keys = jax.random.split(key, accum)

                # probe mutated structure (BN running stats) so the scan
                # can CHAIN stats micro-batch to micro-batch, matching
                # accum sequential batches
                mut_struct = jax.eval_shape(
                    lambda tr, d, l, k: loss_of(tr, d, l, k)[1],
                    trainable,
                    jax.ShapeDtypeStruct(data_m.shape[1:], data_m.dtype),
                    jax.ShapeDtypeStruct(label_m.shape[1:], label_m.dtype),
                    key)
                mut0 = {n: params[n] for n in mut_struct}

                def body(carry, xs):
                    g_acc, loss_acc, mut_state = carry
                    d_mb, l_mb, k_mb = xs
                    (loss, mutated), g = jax.value_and_grad(
                        loss_of, has_aux=True)(trainable, d_mb, l_mb, k_mb,
                                               mut_state)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    # scan carry dtypes must be invariant: under a bf16
                    # compute dtype the stats come back bf16 while the
                    # carry started from the fp32 master copies
                    mutated = {n: arr.astype(mut0[n].dtype)
                               for n, arr in mutated.items()}
                    return (g_acc, loss_acc + loss, mutated), None

                g0 = jax.tree_util.tree_map(
                    lambda w: jnp.zeros(w.shape, jnp.float32), trainable)
                (grads, loss, mutated), _ = lax.scan(
                    body, (g0, jnp.float32(0), mut0), (data_m, label_m, keys))
                inv = 1.0 / accum
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                loss = loss * inv
            new_params = dict(params)
            new_state = dict(opt_state)
            for n in grad_names:
                w, g = params[n], grads[n]
                new_w, st = self._apply_update(n, w, g, opt_state[n], t)
                new_params[n] = new_w.astype(w.dtype)
                new_state[n] = st
            for n, arr in mutated.items():  # BatchNorm running stats etc.
                if n not in grad_names:
                    # stats ride the compute dtype inside the step; the
                    # stored master copy stays in the param's own dtype
                    new_params[n] = arr.astype(params[n].dtype)
            return new_params, new_state, loss

        donate = (0, 1) if self.donate else ()
        return jax.jit(step_fn, donate_argnums=donate)

    def _put(self, x, spec):
        if isinstance(x, NDArray):
            x = x._data
        elif not isinstance(x, jax.Array):
            x = jnp.asarray(x)
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def step(self, data, label, sync: bool = True):
        """One step; all comm is inside jit.  ``sync=True`` returns the host
        loss (a device round-trip per step — the reference's WaitToRead);
        ``sync=False`` returns the device loss array so steps enqueue
        asynchronously back-to-back (the dependency-engine overlap story)."""
        with self.mesh:
            data = self._put(data, self.batch_spec)
            label = self._put(label, self.label_spec)
            sig = (data.shape, str(data.dtype), label.shape, str(label.dtype))
            fn = self._jitted.get(sig)
            if fn is None:
                fn = self._build()
                self._jitted[sig] = fn
            self.step_count += 1
            key = _random.next_key()
            self.params, self.opt_state, loss = fn(
                self.params, self.opt_state, data, label, key,
                jnp.asarray(self.step_count, dtype=jnp.float32))
        return float(loss) if sync else loss

    def stage(self, data, label):
        """Pre-place a batch on the mesh (host->HBM once, reusable)."""
        return (self._put(data, self.batch_spec),
                self._put(label, self.label_spec))

    def sync_to_block(self):
        """Write trained parameters back into the Block's Parameters
        (the reference's kvstore pull-into-weights)."""
        params = self.block.collect_params()
        for n in self.names:
            host = onp.asarray(jax.device_get(self.params[n]))
            for d in params[n]._data:
                dev = next(iter(d._data.devices()))
                d._set_data(jax.device_put(jnp.asarray(host), dev))
        return self
