"""Pod-scale SPMD data parallelism: ``kvstore='tpu'`` as mesh sharding.

The reference scales data-parallel training through KVStore push/pull
(``src/kvstore/``): the Trainer pushes gradients, a comm backend
(NCCL rings / ps-lite servers) reduces them, and the workers pull the
result — a host-driven collective standing OUTSIDE the computation.
TPU-native, the same contract is a *sharding*: parameters and optimizer
state replicate across a named ``jax.sharding.Mesh`` ``'dp'`` axis, the
batch shards over it, and the gradient all-reduce becomes an ICI-native
collective the XLA SPMD partitioner schedules INSIDE the one donated
train-step program (arXiv:2301.13062 — collectives the compiler sees can
overlap backward; arXiv:2008.01040 — padding/placement is where TPU
performance lives).  ``Trainer(..., kvstore='tpu')`` +
``Trainer.compile_step`` route through here with zero user-code changes.

This module owns the placement plumbing shared by ``cached_step``
(training), ``engine.DevicePrefetcher`` (input staging), ``serving``
(replicated inference) and the DataLoader (per-process sharded
sampling):

- :func:`mesh_for_store` — resolve the data-parallel mesh for a kvstore
  type under the ``MXNET_SPMD_MESH`` knob (``auto`` = every visible
  device on the ``'dp'`` axis; an int = that many devices; ``off``
  disables; ``dp=4,tp=2`` spec strings go through
  :func:`mesh.make_mesh`).
- :func:`put_batch` — stage one batch leaf with the batch
  ``NamedSharding`` (site ``spmd.put``, shared retry policy).  Under
  multi-controller the host array is this process's shard of the
  GLOBAL batch (the DataLoader ``num_shards`` contract) and the global
  array assembles via ``jax.make_array_from_process_local_data``.
  A batch axis the mesh cannot divide evenly is REPLICATED instead —
  loudly (:func:`replicated_batch_count` + a warning), never an error
  mid-step and never silent.
- :func:`ensure_placed` — idempotent replicated placement for
  parameters/optimizer state; every actual device_put is counted
  (:func:`reshard_count`) so the dispatch-budget gate can pin
  "0 host-side cross-device copies in steady state".
"""
from __future__ import annotations

import threading
import warnings
from typing import Optional, Tuple

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import config as _config
from .. import faults as _faults
from .mesh import make_mesh

__all__ = ["DATA_AXIS", "mesh_for_store", "resolve_mesh", "batch_sharding",
           "replicated", "batch_spec_for", "put_batch", "ensure_placed",
           "mesh_key", "reshard_count", "replicated_batch_count",
           "reset_counters"]

# the canonical data-parallel axis (mesh.AXIS_NAMES's 'dp'): the KVStore
# axis — gradients all-reduce over it, the batch shards over it
DATA_AXIS = "dp"

# kvstore types whose reduce is the ICI-collective mesh path.  dist/
# ps-lite-style stores stay host-driven and keep the eager fallback.
_MESH_STORES = ("tpu", "nccl")

from .. import telemetry as _telemetry

_lock = threading.Lock()
# param/state leaves actually moved by ensure_placed (first-step placement
# is expected; a steady-state bump is a silent cross-device copy — the
# budget gate pins it at 0 after warmup)
_RESHARD = _telemetry.counter(
    "spmd.reshard",
    "param/state leaves actually moved by ensure_placed (first-step "
    "placement expected; a steady-state bump is a silent cross-device "
    "copy — the budget gate pins it at 0 after warmup)")
# batches replicated because the 'dp' axis could not divide the batch
# axis evenly (correct, but no scale-out for that step — loud by contract)
_REPLICATED_BATCH = _telemetry.counter(
    "spmd.replicated_batch",
    "batches replicated because the 'dp' axis could not divide the "
    "batch axis evenly (correct but no scale-out that step)")
_WARNED_SHAPES: set = set()


def reshard_count() -> int:
    return int(_RESHARD.value)


def replicated_batch_count() -> int:
    return int(_REPLICATED_BATCH.value)


def reset_counters() -> None:
    _RESHARD.reset()
    _REPLICATED_BATCH.reset()


# ---------------------------------------------------------------------------
# mesh resolution (MXNET_SPMD_MESH)
# ---------------------------------------------------------------------------

def _admitted_devices():
    """Visible devices minus the sentinel's active quarantine list (a
    corrupt replica or a heartbeat-suspected rank persisted by a prior
    incarnation): the restart-time exclusion that re-resolves the mesh
    WITHOUT the suspect device.  Excluding everything would leave no
    mesh to train on — that degenerate list is ignored loudly."""
    devices = jax.devices()
    from .. import sentinel as _sentinel

    q = _sentinel.active_quarantine()
    if q is None:
        return devices
    kept = q.filter_devices(devices)
    if not kept:
        warnings.warn(
            "every visible device is quarantined "
            f"(entries: {q.entries()}); ignoring the quarantine list "
            "for mesh resolution", stacklevel=3)
        return devices
    if len(kept) < len(devices):
        excluded = sorted(d.id for d in devices if d not in kept)
        _log_quarantine_exclusion(excluded, q)
    return kept


def _log_quarantine_exclusion(excluded, q) -> None:
    from ..log import get_logger

    get_logger("mxnet_tpu.spmd").warning(
        "mesh resolution excludes quarantined device(s) %s "
        "(quarantine: %s)", excluded, q.entries())
    _telemetry.event("corruption", "spmd.quarantine_excluded",
                     devices=excluded)


def resolve_mesh(spec: Optional[str] = None) -> Optional[Mesh]:
    """Resolve ``MXNET_SPMD_MESH`` (or an explicit spec string) into a
    data-parallel mesh, or ``None`` when SPMD is off.

    - ``auto`` (default): every visible device on the ``'dp'`` axis;
      a single-device world resolves to ``None`` (the plain single-chip
      compiled step — no behavior change off-pod).
    - ``0`` / ``off`` / ``none``: disabled.
    - ``<int>``: that many devices on ``'dp'`` (``1`` gives a real
      1-device mesh — the parity oracle for sharded-vs-single tests).
    - ``dp=4,tp=2`` style: axis spec via :func:`mesh.make_mesh` (the
      compiled step shards the batch over ``'dp'`` only; other axes need
      a ShardingPlan and ride :class:`~.train.ShardedTrainer`).

    Every form resolves over the ADMITTED device set: devices (or whole
    ranks) in the sentinel's persisted quarantine list are excluded, so
    a restart after a localized corruption or a hung host re-places
    onto a mesh without the suspect (the PR-11 topology-change
    machinery, triggered automatically).
    """
    raw = spec if spec is not None else _config.get("MXNET_SPMD_MESH")
    raw = (raw or "auto").strip().lower()
    if raw in ("0", "off", "none", "disabled"):
        return None
    devices = _admitted_devices()
    if raw in ("auto", ""):
        if len(devices) < 2:
            return None
        return make_mesh({DATA_AXIS: len(devices)}, devices)
    if raw.isdigit():
        n = int(raw)
        if n < 1:
            return None
        if n > len(devices):
            raise ValueError(
                f"MXNET_SPMD_MESH={n} needs {n} devices, only "
                f"{len(devices)} visible")
        return make_mesh({DATA_AXIS: n}, devices[:n])
    axes = {}
    for part in raw.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    if DATA_AXIS not in axes:
        raise ValueError(
            f"MXNET_SPMD_MESH={raw!r} must name the '{DATA_AXIS}' axis "
            "(e.g. 'dp=8'), or be 'auto'/'off'/an integer")
    return make_mesh(axes, devices)


def mesh_for_store(kv_type: Optional[str]) -> Optional[Mesh]:
    """The mesh a :class:`~mxnet_tpu.cached_step.TrainStep` should trace
    under for a given kvstore type: the resolved ``MXNET_SPMD_MESH``
    mesh for the ICI-collective stores (``'tpu'``/``'nccl'``), ``None``
    (single-chip path) for everything else."""
    if kv_type is None or kv_type.lower() not in _MESH_STORES:
        return None
    return resolve_mesh()


# ---------------------------------------------------------------------------
# shardings
# ---------------------------------------------------------------------------

def replicated(mesh: Mesh) -> NamedSharding:
    """Params / optimizer state / scalars: one replica per mesh device
    (the KVStore broadcast contract)."""
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical batch placement: axis 0 split over ``'dp'``."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def batch_spec_for(shape: Tuple[int, ...], mesh: Mesh) -> PartitionSpec:
    """Legalized batch spec for one leaf: ``P('dp')`` when the batch
    axis divides evenly, ``P()`` (replicated, counted + warned once per
    shape) otherwise.  Never raises mid-step."""
    n = int(mesh.shape.get(DATA_AXIS, 1))
    if n <= 1 or not shape:
        return PartitionSpec()      # scalars replicate, silently
    if shape[0] % n != 0:
        with _lock:
            _REPLICATED_BATCH.inc()
            key = (tuple(shape), n)
            if key not in _WARNED_SHAPES:
                _WARNED_SHAPES.add(key)
                warnings.warn(
                    f"SPMD batch axis {shape[0]} is not divisible by the "
                    f"{n}-way '{DATA_AXIS}' mesh axis; this input is "
                    "REPLICATED for correctness (no data-parallel speedup "
                    "for it). Pad the batch (e.g. "
                    "DataLoader(last_batch='pad')) or pick a divisible "
                    "batch size.", stacklevel=3)
        return PartitionSpec()
    return PartitionSpec(DATA_AXIS)


def mesh_key(mesh: Optional[Mesh]):
    """Hashable program-cache key component: the mesh's axes and exact
    device set (a different topology must never reuse a program)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------

def _equivalently_placed(arr, sharding: NamedSharding) -> bool:
    cur = getattr(arr, "sharding", None)
    if cur is None:
        return False
    # uncommitted arrays sit on the default device only by accident —
    # they must be pinned to the mesh explicitly once
    if not getattr(arr, "committed", True):
        return False
    try:
        return cur.is_equivalent_to(sharding, arr.ndim)
    except Exception:
        return False


def ensure_placed(arr: jax.Array, sharding: NamedSharding) -> jax.Array:
    """Idempotent placement: return ``arr`` untouched when it already
    carries an equivalent sharding, else ``device_put`` it (counted in
    :func:`reshard_count` — steady state must not pay this)."""
    if _equivalently_placed(arr, sharding):
        return arr
    _RESHARD.inc()
    return jax.device_put(arr, sharding)


def _put_batch_once(arr, sharding: NamedSharding):
    if jax.process_count() > 1:
        # multi-controller: ``arr`` is this process's contiguous shard of
        # the global batch (the DataLoader num_shards contract); assemble
        # the global jax.Array from per-process local data
        # graftlint: disable=host-sync -- ``arr`` is the HOST batch shard
        # being staged to device, not a device array read back
        return jax.make_array_from_process_local_data(
            sharding, onp.asarray(arr))
    return jax.device_put(arr, sharding)


def put_batch(arr, mesh: Mesh):
    """Stage one batch leaf onto the mesh with the legalized batch
    sharding (already-staged leaves — the DevicePrefetcher path — pass
    through untouched).  A transient transfer failure retries under the
    shared policy (site ``spmd.put``), mirroring ``engine.prefetch``."""
    shape = tuple(getattr(arr, "shape", ()))
    sharding = NamedSharding(mesh, batch_spec_for(shape, mesh))
    if isinstance(arr, jax.Array) and _equivalently_placed(arr, sharding):
        return arr
    return _faults.retry_call(_put_batch_once, arr, sharding,
                              site="spmd.put")
