"""Pod-scale SPMD data parallelism: ``kvstore='tpu'`` as mesh sharding.

The reference scales data-parallel training through KVStore push/pull
(``src/kvstore/``): the Trainer pushes gradients, a comm backend
(NCCL rings / ps-lite servers) reduces them, and the workers pull the
result — a host-driven collective standing OUTSIDE the computation.
TPU-native, the same contract is a *sharding*: parameters and optimizer
state replicate across a named ``jax.sharding.Mesh`` ``'dp'`` axis, the
batch shards over it, and the gradient all-reduce becomes an ICI-native
collective the XLA SPMD partitioner schedules INSIDE the one donated
train-step program (arXiv:2301.13062 — collectives the compiler sees can
overlap backward; arXiv:2008.01040 — padding/placement is where TPU
performance lives).  ``Trainer(..., kvstore='tpu')`` +
``Trainer.compile_step`` route through here with zero user-code changes.

Beyond pure data parallelism the same one-program contract covers the
model-parallel axes: an ``fsdp`` mesh axis shards parameters and
optimizer state (ZeRO-3 style — :func:`param_spec` picks each leaf's
largest evenly-divisible dim, indivisible leaves replicate LOUDLY via
the ``sharding.legalize_refusal`` idiom), and a ``tp`` axis carries
``sharding.constraint`` annotations from model code through the traced
step.  In every case the scatter/gather/all-reduce schedule belongs to
the XLA SPMD partitioner INSIDE the one donated program — still 1
dispatch/step, 0 retraces, 0 host-side cross-device copies.

This module owns the placement plumbing shared by ``cached_step``
(training), ``engine.DevicePrefetcher`` (input staging), ``serving``
(replicated inference) and the DataLoader (per-process sharded
sampling):

- :func:`mesh_for_store` — resolve the mesh for a kvstore type under
  the ``MXNET_SPMD_MESH`` knob (``auto`` = every visible device on the
  ``'dp'`` axis; an int = that many devices; ``off`` disables;
  ``dp=4,fsdp=2`` axis-spec strings go through :func:`mesh.make_mesh`
  — the compiled step shards the batch over ``'dp'`` only, params/
  optimizer state over ``'fsdp'``, and leaves ``'tp'`` placement to
  model-code :func:`~.sharding.constraint` calls).
- :func:`put_batch` — stage one batch leaf with the batch
  ``NamedSharding`` (site ``spmd.put``, shared retry policy).  Under
  multi-controller the host array is this process's shard of the
  GLOBAL batch (the DataLoader ``num_shards`` contract) and the global
  array assembles via ``jax.make_array_from_process_local_data``.
  A batch axis the mesh cannot divide evenly is REPLICATED instead —
  loudly (:func:`replicated_batch_count` + a warning), never an error
  mid-step and never silent.
- :func:`ensure_placed` — idempotent replicated placement for
  parameters/optimizer state; every actual device_put is counted
  (:func:`reshard_count`) so the dispatch-budget gate can pin
  "0 host-side cross-device copies in steady state".
"""
from __future__ import annotations

import re as _re
import threading
import warnings
from typing import Optional, Tuple

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import config as _config
from .. import faults as _faults
from .mesh import make_mesh

__all__ = ["DATA_AXIS", "MODEL_AXIS", "TENSOR_AXIS", "PIPE_AXIS",
           "EXPERT_AXIS", "model_axes_active", "mesh_for_store",
           "resolve_mesh", "batch_sharding", "replicated", "batch_spec_for",
           "param_spec", "param_sharding", "put_batch", "ensure_placed",
           "mesh_key", "reshard_count", "replicated_batch_count",
           "record_layout", "param_bytes_per_device",
           "opt_bytes_per_device", "reset_counters"]

# the canonical data-parallel axis (mesh.AXIS_NAMES's 'dp'): the KVStore
# axis — gradients all-reduce over it, the batch shards over it
DATA_AXIS = "dp"
# the parameter-sharding axis (ZeRO/FSDP): params + optimizer state
# shard over it, the batch does NOT
MODEL_AXIS = "fsdp"
# the tensor-parallel axis: placement is model-code's move (via
# sharding.constraint / a ShardingPlan), never implied by this module
TENSOR_AXIS = "tp"
# the pipeline axis: HeteroPipeline's packed [n_stages, P] stage buffer
# shards dim 0 over it (device i holds stage i's weights); matched BY
# NAME in param_spec — the packed parameter is canonically 'pp_stages'
PIPE_AXIS = "pp"
# the expert-parallel axis: MoE expert weights ([E, ...] leaves under an
# 'expert.' structural prefix) shard dim 0 over it
EXPERT_AXIS = "ep"

# name-aware placement rules (param_spec): structural parameter names
# matching these regexes take first-class-axis placement before the
# shape-only FSDP rule is consulted
_PIPE_PACKED_RE = _re.compile(r"(^|\.)pp_stages$")
_EXPERT_RE = _re.compile(r"(^|\.)expert\.")

# kvstore types whose reduce is the ICI-collective mesh path.  dist/
# ps-lite-style stores stay host-driven and keep the eager fallback.
_MESH_STORES = ("tpu", "nccl")

from .. import telemetry as _telemetry

_lock = threading.Lock()
# param/state leaves actually moved by ensure_placed (first-step placement
# is expected; a steady-state bump is a silent cross-device copy — the
# budget gate pins it at 0 after warmup)
_RESHARD = _telemetry.counter(
    "spmd.reshard",
    "param/state leaves actually moved by ensure_placed (first-step "
    "placement expected; a steady-state bump is a silent cross-device "
    "copy — the budget gate pins it at 0 after warmup)")
# batches replicated because the 'dp' axis could not divide the batch
# axis evenly (correct, but no scale-out for that step — loud by contract)
_REPLICATED_BATCH = _telemetry.counter(
    "spmd.replicated_batch",
    "batches replicated because the 'dp' axis could not divide the "
    "batch axis evenly (correct but no scale-out that step)")
_WARNED_SHAPES: set = set()

# per-device memory accounting: the byte footprint of the CURRENT
# parameter / optimizer-state layout on ONE device, computed from each
# placed leaf's actual sharding (shard_shape) — so an fsdp-sharded
# layout reads ~1/N of the replicated one.  Recorded by the TrainStep
# warmup (record_layout), surfaced as computed gauges in
# telemetry.report() and stamped into the MULTICHIP bench lanes.
_LAYOUT_BYTES = {"param": 0, "opt": 0}


def _leaf_bytes_per_device(arr) -> int:
    """One leaf's bytes on ONE device: the shard shape (under its actual
    sharding) × itemsize.  Unplaced/host leaves count their full size."""
    shape = tuple(int(s) for s in getattr(arr, "shape", ()))
    sh = getattr(arr, "sharding", None)
    if sh is not None:
        try:
            shape = tuple(int(s) for s in sh.shard_shape(shape))
        except Exception:
            pass
    n = 1
    for s in shape:
        n *= s
    itemsize = getattr(getattr(arr, "dtype", None), "itemsize", 4)
    return n * int(itemsize)


def record_layout(param_leaves, opt_leaves) -> None:
    """Record the per-device byte footprint of the placed parameter and
    optimizer-state layout (TrainStep warmup calls this after
    placement; single-chip layouts record their full size)."""
    p = sum(_leaf_bytes_per_device(a) for a in param_leaves)
    o = sum(_leaf_bytes_per_device(a) for a in opt_leaves)
    with _lock:
        _LAYOUT_BYTES["param"] = int(p)
        _LAYOUT_BYTES["opt"] = int(o)


def param_bytes_per_device() -> int:
    """Bytes of parameters resident on ONE device under the current
    layout (gauge ``spmd.param_bytes_per_device``)."""
    return int(_LAYOUT_BYTES["param"])


def opt_bytes_per_device() -> int:
    """Bytes of optimizer state resident on ONE device under the
    current layout (gauge ``spmd.opt_bytes_per_device``)."""
    return int(_LAYOUT_BYTES["opt"])


_telemetry.gauge_fn(
    "spmd.param_bytes_per_device", param_bytes_per_device,
    "bytes of parameters resident on one device under the current "
    "layout (replicated: the full model; fsdp-sharded: ~1/N)")
_telemetry.gauge_fn(
    "spmd.opt_bytes_per_device", opt_bytes_per_device,
    "bytes of optimizer state resident on one device under the current "
    "layout (replicated: the full state; fsdp-sharded: ~1/N)")


def reshard_count() -> int:
    return int(_RESHARD.value)


def replicated_batch_count() -> int:
    return int(_REPLICATED_BATCH.value)


def reset_counters() -> None:
    _RESHARD.reset()
    _REPLICATED_BATCH.reset()
    with _lock:
        _LAYOUT_BYTES["param"] = 0
        _LAYOUT_BYTES["opt"] = 0


# ---------------------------------------------------------------------------
# mesh resolution (MXNET_SPMD_MESH)
# ---------------------------------------------------------------------------

def _admitted_devices():
    """Visible devices minus the sentinel's active quarantine list (a
    corrupt replica or a heartbeat-suspected rank persisted by a prior
    incarnation): the restart-time exclusion that re-resolves the mesh
    WITHOUT the suspect device.  Excluding everything would leave no
    mesh to train on — that degenerate list is ignored loudly."""
    devices = jax.devices()
    from .. import sentinel as _sentinel

    q = _sentinel.active_quarantine()
    if q is None:
        return devices
    kept = q.filter_devices(devices)
    if not kept:
        warnings.warn(
            "every visible device is quarantined "
            f"(entries: {q.entries()}); ignoring the quarantine list "
            "for mesh resolution", stacklevel=3)
        return devices
    if len(kept) < len(devices):
        excluded = sorted(d.id for d in devices if d not in kept)
        _log_quarantine_exclusion(excluded, q)
    return kept


def _log_quarantine_exclusion(excluded, q) -> None:
    from ..log import get_logger

    get_logger("mxnet_tpu.spmd").warning(
        "mesh resolution excludes quarantined device(s) %s "
        "(quarantine: %s)", excluded, q.entries())
    _telemetry.event("corruption", "spmd.quarantine_excluded",
                     devices=excluded)


def resolve_mesh(spec: Optional[str] = None) -> Optional[Mesh]:
    """Resolve ``MXNET_SPMD_MESH`` (or an explicit spec string) into a
    data-parallel mesh, or ``None`` when SPMD is off.

    - ``auto`` (default): every visible device on the ``'dp'`` axis;
      a single-device world resolves to ``None`` (the plain single-chip
      compiled step — no behavior change off-pod).
    - ``0`` / ``off`` / ``none``: disabled.
    - ``<int>``: that many devices on ``'dp'`` (``1`` gives a real
      1-device mesh — the parity oracle for sharded-vs-single tests).
    - ``dp=4,fsdp=2`` style: axis spec via :func:`mesh.make_mesh`.  The
      compiled step shards the batch over ``'dp'`` ONLY; an ``fsdp``
      axis shards params + optimizer state (:func:`param_spec`); a
      ``tp`` axis is left to model-code ``sharding.constraint`` calls,
      which resolve against this mesh inside the traced step.  Axes
      compose on one mesh (``dp=2,fsdp=2,tp=2`` needs 8 devices).

    Every form resolves over the ADMITTED device set: devices (or whole
    ranks) in the sentinel's persisted quarantine list are excluded, so
    a restart after a localized corruption or a hung host re-places
    onto a mesh without the suspect (the PR-11 topology-change
    machinery, triggered automatically).
    """
    raw = spec if spec is not None else _config.get("MXNET_SPMD_MESH")
    raw = (raw or "auto").strip().lower()
    if raw in ("0", "off", "none", "disabled"):
        return None
    devices = _admitted_devices()
    if raw in ("auto", ""):
        if len(devices) < 2:
            return None
        return make_mesh({DATA_AXIS: len(devices)}, devices)
    if raw.isdigit():
        n = int(raw)
        if n < 1:
            return None
        if n > len(devices):
            raise ValueError(
                f"MXNET_SPMD_MESH={n} needs {n} devices, only "
                f"{len(devices)} visible")
        return make_mesh({DATA_AXIS: n}, devices[:n])
    axes = {}
    for part in raw.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    if DATA_AXIS not in axes:
        raise ValueError(
            f"MXNET_SPMD_MESH={raw!r} must name the '{DATA_AXIS}' axis "
            "(e.g. 'dp=8'), or be 'auto'/'off'/an integer")
    return make_mesh(axes, devices)


def mesh_for_store(kv_type: Optional[str]) -> Optional[Mesh]:
    """The mesh a :class:`~mxnet_tpu.cached_step.TrainStep` should trace
    under for a given kvstore type: the resolved ``MXNET_SPMD_MESH``
    mesh for the ICI-collective stores (``'tpu'``/``'nccl'``), ``None``
    (single-chip path) for everything else."""
    if kv_type is None or kv_type.lower() not in _MESH_STORES:
        return None
    return resolve_mesh()


# ---------------------------------------------------------------------------
# shardings
# ---------------------------------------------------------------------------

def replicated(mesh: Mesh) -> NamedSharding:
    """Params / optimizer state / scalars: one replica per mesh device
    (the KVStore broadcast contract)."""
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical batch placement: axis 0 split over ``'dp'`` — and
    ONLY ``'dp'``; a multi-axis mesh (``fsdp``/``tp``) never shards the
    batch over its model axes."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def param_spec(shape: Tuple[int, ...], mesh: Mesh,
               min_size: Optional[int] = None,
               name: Optional[str] = None) -> PartitionSpec:
    """Placement rule for one parameter / optimizer-state leaf.

    Name-aware first-class-axis rules run first (``name`` is the
    structural parameter name when the caller knows it):

    - ``pp_stages`` (HeteroPipeline's packed ``[n_stages, P]`` stage
      buffer) → ``P('pp', None)`` when the mesh's ``pp`` axis equals the
      stage count — device *i* holds stage *i*'s packed weights;
    - ``expert.*`` leaves (MoE expert weights ``[E, ...]``) →
      ``P('ep')`` on dim 0 when ``ep`` divides the expert count.

    Otherwise the FSDP/ZeRO rule: shard the LARGEST dim the ``'fsdp'``
    axis divides evenly.  Leaves below ``min_size`` elements
    (``MXNET_FSDP_MIN_SIZE``) stay replicated — sharding a LayerNorm
    bias buys nothing and costs an all-gather.  A large leaf NO dim of
    which divides the axis degrades to replication LOUDLY via the
    ``sharding.legalize_refusal`` idiom (counted + warned once per
    shape), never an error mid-warmup."""
    if name and shape:
        n_pp = int(mesh.shape.get(PIPE_AXIS, 1))
        if n_pp > 1 and _PIPE_PACKED_RE.search(name) \
                and shape[0] == n_pp:
            return PartitionSpec(PIPE_AXIS,
                                 *([None] * (len(shape) - 1)))
        n_ep = int(mesh.shape.get(EXPERT_AXIS, 1))
        if n_ep > 1 and _EXPERT_RE.search(name) \
                and shape[0] % n_ep == 0:
            return PartitionSpec(EXPERT_AXIS,
                                 *([None] * (len(shape) - 1)))
    if min_size is None:
        min_size = int(_config.get("MXNET_FSDP_MIN_SIZE"))
    n = int(mesh.shape.get(MODEL_AXIS, 1))
    if n <= 1 or not shape:
        return PartitionSpec()
    size = 1
    for s in shape:
        size *= int(s)
    if size < min_size:
        return PartitionSpec()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n == 0:
            spec = [None] * len(shape)
            spec[i] = MODEL_AXIS
            return PartitionSpec(*spec)
    # no dim divides the axis: the loudly-replicated fallback
    from .sharding import _legalize

    return _legalize(PartitionSpec(MODEL_AXIS), tuple(shape), mesh,
                     loud=True)


def param_sharding(shape: Tuple[int, ...], mesh: Mesh,
                   name: Optional[str] = None) -> NamedSharding:
    """The ``NamedSharding`` a param/state leaf of ``shape`` takes on
    ``mesh``: :func:`param_spec` (name-aware pp/ep rules, then the
    ``fsdp`` shape rule), replicated otherwise."""
    return NamedSharding(mesh, param_spec(shape, mesh, name=name))


def model_axes_active(mesh: Mesh) -> bool:
    """True when any model-side placement axis (``fsdp``/``pp``/``ep``)
    is real (> 1) on ``mesh`` — the gate for per-leaf name/shape-aware
    parameter placement in the compiled step."""
    return any(int(mesh.shape.get(a, 1)) > 1
               for a in (MODEL_AXIS, PIPE_AXIS, EXPERT_AXIS))


def batch_spec_for(shape: Tuple[int, ...], mesh: Mesh) -> PartitionSpec:
    """Legalized batch spec for one leaf: ``P('dp')`` when the batch
    axis divides evenly, ``P()`` (replicated, counted + warned once per
    shape) otherwise.  Never raises mid-step."""
    n = int(mesh.shape.get(DATA_AXIS, 1))
    if n <= 1 or not shape:
        return PartitionSpec()      # scalars replicate, silently
    if shape[0] % n != 0:
        with _lock:
            _REPLICATED_BATCH.inc()
            key = (tuple(shape), n)
            if key not in _WARNED_SHAPES:
                _WARNED_SHAPES.add(key)
                warnings.warn(
                    f"SPMD batch axis {shape[0]} is not divisible by the "
                    f"{n}-way '{DATA_AXIS}' mesh axis; this input is "
                    "REPLICATED for correctness (no data-parallel speedup "
                    "for it). Pad the batch (e.g. "
                    "DataLoader(last_batch='pad')) or pick a divisible "
                    "batch size.", stacklevel=3)
        return PartitionSpec()
    return PartitionSpec(DATA_AXIS)


def mesh_key(mesh: Optional[Mesh]):
    """Hashable program-cache key component: the mesh's axes and exact
    device set (a different topology must never reuse a program)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------

def _equivalently_placed(arr, sharding: NamedSharding) -> bool:
    cur = getattr(arr, "sharding", None)
    if cur is None:
        return False
    # uncommitted arrays sit on the default device only by accident —
    # they must be pinned to the mesh explicitly once
    if not getattr(arr, "committed", True):
        return False
    try:
        return cur.is_equivalent_to(sharding, arr.ndim)
    except Exception:
        return False


def ensure_placed(arr: jax.Array, sharding: NamedSharding) -> jax.Array:
    """Idempotent placement: return ``arr`` untouched when it already
    carries an equivalent sharding, else ``device_put`` it (counted in
    :func:`reshard_count` — steady state must not pay this)."""
    if _equivalently_placed(arr, sharding):
        return arr
    _RESHARD.inc()
    return jax.device_put(arr, sharding)


def _put_batch_once(arr, sharding: NamedSharding):
    if jax.process_count() > 1:
        # multi-controller: ``arr`` is this process's contiguous shard of
        # the global batch (the DataLoader num_shards contract); assemble
        # the global jax.Array from per-process local data
        # graftlint: disable=host-sync -- ``arr`` is the HOST batch shard
        # being staged to device, not a device array read back
        return jax.make_array_from_process_local_data(
            sharding, onp.asarray(arr))
    return jax.device_put(arr, sharding)


def put_batch(arr, mesh: Mesh):
    """Stage one batch leaf onto the mesh with the legalized batch
    sharding (already-staged leaves — the DevicePrefetcher path — pass
    through untouched).  A transient transfer failure retries under the
    shared policy (site ``spmd.put``), mirroring ``engine.prefetch``."""
    shape = tuple(getattr(arr, "shape", ()))
    sharding = NamedSharding(mesh, batch_spec_for(shape, mesh))
    if isinstance(arr, jax.Array) and _equivalently_placed(arr, sharding):
        return arr
    return _faults.retry_call(_put_batch_once, arr, sharding,
                              site="spmd.put")
