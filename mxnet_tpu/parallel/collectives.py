"""Named collectives over mesh axes.

The reference's communication layer is imperative: CommCPU/CommDevice reduce
buffers (src/kvstore/comm.h:104-556), KVStoreNCCL issues ncclReduce/Bcast
(src/kvstore/kvstore_nccl.h), ps-lite RPCs for multi-node.  On TPU these are
XLA collectives over ICI/DCN, expressed with ``jax.lax`` primitives inside
``shard_map``/``pjit`` regions.  This module gives them KVStore-flavoured
names so higher layers (kvstore='tpu'/'dist', ring attention, MoE dispatch)
read like the survey's component inventory.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
try:
    from jax import shard_map as _jax_shard_map
except ImportError:      # this jax ships it under experimental
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(*args, **kwargs):
    """shard_map with the check_vma kwarg mapped onto older jax's
    check_rep spelling (renamed upstream; semantics unchanged here)."""
    try:
        return _jax_shard_map(*args, **kwargs)
    except TypeError:
        if "check_vma" in kwargs:
            kwargs = dict(kwargs)
            kwargs["check_rep"] = kwargs.pop("check_vma")
            return _jax_shard_map(*args, **kwargs)
        raise

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
    "ring_shift", "axis_index", "axis_size", "broadcast_from", "pmean",
    "run_sharded",
]


def all_reduce(x, axis_name: str, op: str = "sum"):
    """CommDevice::Reduce + Broadcast fused (comm.h:504) = one all-reduce."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def pmean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift(x, axis_name: str, shift: int = 1, *, size: Optional[int] = None):
    """Rotate shards around the ring — the primitive under ring attention
    and pipeline bubbles; rides neighbour ICI links."""
    if size is None:
        size = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % size) for i in range(size)]
    return lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.psum(1, axis_name)


def broadcast_from(x, axis_name: str, src: int = 0):
    """KVStore Broadcast analog: every member gets src's shard (masked
    all-reduce; XLA lowers this to a broadcast-shaped collective)."""
    is_src = lax.axis_index(axis_name) == src
    # select (not multiply): non-source shards may hold inf/NaN garbage and
    # 0*inf would poison the psum
    return lax.psum(jnp.where(is_src, x, jnp.zeros_like(x)), axis_name)


def run_sharded(fn: Callable, mesh: Mesh, in_specs, out_specs,
                check_vma: bool = False):
    """Wrap ``fn`` with shard_map over ``mesh`` — the escape hatch when XLA's
    automatic partitioning shouldn't own the schedule (ring attention,
    pipeline loops)."""
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=check_vma)
