"""Sharding plans: parameter-name patterns → PartitionSpec.

The reference's distribution story is value-level (KVStore decides where each
parameter lives, src/kvstore/kvstore_local.h key grouping).  Here placement is
declarative: a ``ShardingPlan`` is an ordered rule list matched against the
structural parameter name (the same names ``Block.collect_params`` produces),
yielding a ``PartitionSpec``.  Rules that don't divide the actual shape fall
back to replication on the offending axis — the analog of the reference's
big-array splitting guard (``MXNET_KVSTORE_BIGARRAY_BOUND``,
src/kvstore/kvstore_dist.h:44) where non-conforming tensors degrade
gracefully instead of erroring.
"""
from __future__ import annotations

import re
import warnings
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ShardingPlan", "fsdp_plan", "tensor_parallel_plan",
           "expert_parallel_plan", "replicated_plan", "shard_array",
           "constraint", "legalize_refusal_count",
           "reset_legalize_refusals"]

Spec = PartitionSpec

# legalization observability: every spec dim REFUSED (replicated) because
# the shape could not divide the mesh axis evenly.  Refusal is the
# mid-trace-safe half of "pad-or-refuse": a traced value's shape is
# frozen, so padding belongs to the batch boundary (DataLoader
# last_batch='pad', serving buckets) — here the offending dim degrades
# to replication, counted and (on the constraint path) warned.
from .. import telemetry as _telemetry  # noqa: E402

_LEGALIZE_REFUSAL = _telemetry.counter(
    "sharding.legalize_refusal",
    "spec dims refused (degraded to replication) because the shape "
    "could not divide the mesh axis evenly")
_WARNED_REFUSALS: set = set()


def legalize_refusal_count() -> int:
    return int(_LEGALIZE_REFUSAL.value)


def reset_legalize_refusals() -> None:
    _LEGALIZE_REFUSAL.reset()


def _axis_size(mesh: Mesh, axes) -> int:
    if axes is None:
        return 1
    if isinstance(axes, (tuple, list)):
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        return n
    return mesh.shape[axes]


def _legalize(spec: PartitionSpec, shape: Tuple[int, ...], mesh: Mesh,
              loud: bool = False) -> PartitionSpec:
    """Drop sharding on dims the shape can't evenly divide, and on axes the
    mesh doesn't have.  Divisibility refusals are counted
    (:func:`legalize_refusal_count`) and, with ``loud=True`` (the
    :func:`constraint` path), warned once per (shape, spec) — degrading a
    constraint must never be silent, and erroring mid-trace is worse."""
    out = []
    padded = (tuple(spec) + (None,) * len(shape))[: len(shape)]
    for i, axes in enumerate(padded):
        if axes is None:
            out.append(None)
            continue
        ax_tuple = axes if isinstance(axes, (tuple, list)) else (axes,)
        ax_tuple = tuple(a for a in ax_tuple if a in mesh.shape)
        if not ax_tuple:
            out.append(None)
            continue
        n = _axis_size(mesh, ax_tuple)
        if n == 1:
            out.append(None)
        elif shape[i] % n != 0:
            _LEGALIZE_REFUSAL.inc()
            if loud:
                key = (tuple(shape), i, ax_tuple, n)
                if key not in _WARNED_REFUSALS:
                    _WARNED_REFUSALS.add(key)
                    warnings.warn(
                        f"sharding constraint refused on dim {i} of shape "
                        f"{tuple(shape)}: {shape[i]} is not divisible by "
                        f"the {n}-way mesh axis {ax_tuple} — dim "
                        "REPLICATED instead (pad the value at the batch "
                        "boundary, e.g. DataLoader(last_batch='pad') or "
                        "a bucket grid, to shard it)", stacklevel=4)
            out.append(None)
        else:
            out.append(ax_tuple[0] if len(ax_tuple) == 1 else ax_tuple)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


class ShardingPlan:
    """Ordered (regex, PartitionSpec) rules; first match wins."""

    def __init__(self, rules: Sequence[Tuple[str, PartitionSpec]] = (),
                 default: PartitionSpec = PartitionSpec()):
        self.rules: List[Tuple[re.Pattern, PartitionSpec]] = [
            (re.compile(pat), spec) for pat, spec in rules
        ]
        self.default = default

    def add(self, pattern: str, spec: PartitionSpec) -> "ShardingPlan":
        self.rules.append((re.compile(pattern), spec))
        return self

    def extend(self, other: "ShardingPlan") -> "ShardingPlan":
        self.rules.extend(other.rules)
        return self

    def spec_for(self, name: str, shape: Tuple[int, ...], mesh: Mesh) -> PartitionSpec:
        for pat, spec in self.rules:
            if pat.search(name):
                return _legalize(spec, shape, mesh)
        return _legalize(self.default, shape, mesh)

    def shard(self, name: str, arr: jax.Array, mesh: Mesh) -> jax.Array:
        spec = self.spec_for(name, tuple(arr.shape), mesh)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    def shard_tree(self, params: Dict[str, jax.Array], mesh: Mesh
                   ) -> Dict[str, jax.Array]:
        return {n: self.shard(n, a, mesh) for n, a in params.items()}

    def specs_tree(self, params: Dict[str, jax.Array], mesh: Mesh
                   ) -> Dict[str, PartitionSpec]:
        return {n: self.spec_for(n, tuple(a.shape), mesh)
                for n, a in params.items()}


def replicated_plan() -> ShardingPlan:
    """Pure data parallelism: every parameter replicated (the reference's
    KVStore broadcast semantics, comm.h Broadcast)."""
    return ShardingPlan()


def fsdp_plan(axis: str = "fsdp", min_size: int = 1024) -> ShardingPlan:
    """ZeRO-3 style: shard every parameter's largest dim over ``axis``.

    Implemented as a dynamic plan (shape-dependent), so spec_for is
    overridden rather than rule-driven.
    """

    class _FSDP(ShardingPlan):
        def spec_for(self, name, shape, mesh):
            for pat, spec in self.rules:
                if pat.search(name):
                    return _legalize(spec, shape, mesh)
            if not shape:
                return PartitionSpec()
            n = mesh.shape.get(axis, 1)
            size = 1
            for s in shape:
                size *= s
            if n == 1 or size < min_size:
                return PartitionSpec()
            # shard the largest evenly-divisible dim
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % n == 0:
                    spec = [None] * len(shape)
                    spec[i] = axis
                    return PartitionSpec(*spec)
            return PartitionSpec()

    return _FSDP()


def expert_parallel_plan(axis: str = "ep") -> ShardingPlan:
    """Expert parallelism (parallel/moe.py): expert weights — ``[E, ...]``
    leaves under an ``expert.`` structural prefix — shard dim 0 over
    ``axis``; everything else (gate, dense trunk) replicates.  The plan
    form of the name-aware ``spmd.param_spec`` ep rule, for callers that
    place params through a ShardingPlan."""
    return ShardingPlan([
        (r"(^|\.)expert\..*", PartitionSpec(axis)),
        (r".*", PartitionSpec()),
    ])


def tensor_parallel_plan(axis: str = "tp") -> ShardingPlan:
    """Megatron-style transformer sharding by structural-name convention:

    - qkv / gate+up projections: shard output features (column parallel)
    - attention output / MLP down projection: shard input features (row
      parallel) — XLA inserts the all-reduce after the matmul
    - embeddings: shard vocab dim
    - norms / biases of row-parallel layers: replicated
    """
    return ShardingPlan([
        (r"(qkv|query|key|value|q_proj|k_proj|v_proj|ffn_1|fc1|up|gate|inter)"
         r".*weight$", Spec(axis, None)),
        (r"(qkv|query|key|value|q_proj|k_proj|v_proj|ffn_1|fc1|up|gate|inter)"
         r".*bias$", Spec(axis)),
        (r"(out_proj|o_proj|proj|ffn_2|fc2|down|output).*weight$",
         Spec(None, axis)),
        (r"embed.*weight$", Spec(axis, None)),
    ])


def shard_array(arr: jax.Array, mesh: Mesh, spec: PartitionSpec) -> jax.Array:
    return jax.device_put(arr, NamedSharding(mesh, _legalize(spec, tuple(arr.shape), mesh)))


def _ambient_mesh():
    """The mesh jax itself already has in scope — works INSIDE a traced
    fn, where no explicit mesh was threaded through: first the classic
    ``with mesh:`` context (thread_resources physical mesh — what
    ``mesh_scope`` enters), then the newer abstract-mesh ambient
    (``jax.sharding.get_abstract_mesh``, private fallback on older jax).
    Returns ``None`` when there is genuinely no mesh anywhere."""
    try:
        from jax._src import mesh as _jm

        pm = _jm.thread_resources.env.physical_mesh
        if pm is not None and not getattr(pm, "empty", True):
            return pm
    except Exception:
        pass
    get_ambient = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_ambient is None:
        try:
            from jax._src.mesh import get_abstract_mesh as get_ambient
        except ImportError:
            get_ambient = None
    ambient = get_ambient() if get_ambient is not None else None
    if ambient is not None and getattr(ambient, "shape", None):
        return ambient
    return None


def constraint(x, spec: Union[PartitionSpec, Sequence], mesh: Optional[Mesh] = None):
    """``lax.with_sharding_constraint`` that keeps model code
    mesh-agnostic and mid-trace-safe:

    - ``mesh=None`` resolves the ENCLOSING mesh — ``mesh_scope``'s
      current mesh, the ``with mesh:`` jax context, or the abstract
      ambient mesh — so a constraint inside a traced fn never needs the
      mesh threaded through the call stack.  No mesh anywhere: no-op.
    - The spec is legalized against the value's (static) shape before it
      reaches XLA: a dim the mesh axis cannot divide evenly is REFUSED
      (replicated) loudly — warned + counted in
      :func:`legalize_refusal_count` — instead of erroring mid-trace.
      Padding is the caller's move, at the batch boundary.
    - A spec naming an axis the mesh does not have still raises — a
      typo'd axis must not silently drop the constraint.
    - NDArray wrappers pass through transparently (unwrapped,
      constrained, re-wrapped), so model code can pin an activation or
      weight layout inside a hybridizable ``forward`` — the compiled
      train step traces and dispatches inside the mesh context, so the
      annotation reaches the XLA partitioner (the tensor-parallel
      path: ``constraint(h, ('dp', 'tp'))`` on a hidden activation).
    """
    data = getattr(x, "_data", None)
    if data is not None and hasattr(x, "ctx"):
        from ..ndarray import ndarray as _ndmod

        out = constraint(data, spec, mesh)
        return _ndmod._wrap(out, x.ctx, type(x))
    if mesh is None:
        from .mesh import current_mesh

        mesh = current_mesh()
    if mesh is None:
        mesh = _ambient_mesh()
    if mesh is None or not getattr(mesh, "shape", None):
        return x  # no mesh anywhere: mesh-agnostic no-op
    spec = spec if isinstance(spec, PartitionSpec) else PartitionSpec(*spec)
    # canonical axes (mesh.AXIS_NAMES) the mesh does not carry are
    # size-1 by convention and legalize away silently — a model
    # annotated for 'tp' still runs on a pure-dp mesh (the parity
    # oracle).  A NON-canonical name is a typo and must raise.
    from .mesh import AXIS_NAMES

    known = set(mesh.shape) | set(AXIS_NAMES)
    for axes in tuple(spec):
        for a in (axes if isinstance(axes, (tuple, list)) else (axes,)):
            if a is not None and a not in known:
                raise ValueError(
                    f"sharding constraint names axis {a!r} but the mesh "
                    f"in scope only has {sorted(mesh.shape)} (canonical "
                    f"axes {AXIS_NAMES} legalize away when absent) — a "
                    "typo'd axis must not silently drop the constraint")
    lspec = _legalize(spec, tuple(getattr(x, "shape", ())), mesh, loud=True)
    if isinstance(mesh, Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, lspec))
    # abstract ambient mesh: a bare PartitionSpec resolves against it
    return jax.lax.with_sharding_constraint(x, lspec)
