"""Device-mesh management.

The reference scales out through KVStore backends over NCCL/ps-lite
(SURVEY.md §2.3, src/kvstore/).  The TPU-native design instead expresses
*all* parallelism as shardings of one SPMD program over a named
``jax.sharding.Mesh``; XLA inserts the collectives (all-reduce over ICI for
the data-parallel axis = the CommDevice/NCCL analog, all-to-all for expert
dispatch, collective-permute for pipeline/ring axes).

Canonical axis names (any subset may be present, size-1 axes are free):

- ``dp``   data parallel (gradient all-reduce; the KVStore axis)
- ``fsdp`` fully-sharded data parallel (param/optimizer-state sharding)
- ``tp``   tensor (a.k.a. model) parallel within layers
- ``sp``   sequence/context parallel (ring attention)
- ``ep``   expert parallel (MoE)
- ``pp``   pipeline parallel (stage per mesh slice)
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as onp
from jax.sharding import Mesh

__all__ = ["AXIS_NAMES", "make_mesh", "current_mesh", "set_mesh", "mesh_scope",
           "auto_mesh"]

AXIS_NAMES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

_CURRENT: List[Optional[Mesh]] = [None]


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Create a named mesh from ``{axis: size}``.

    Axis order follows AXIS_NAMES so that the fastest-varying (innermost)
    device dimension is ``tp`` — on hardware, adjacent devices share the
    highest ICI bandwidth, which is where tensor-parallel collectives live.
    Unknown axis names are appended in given order.
    """
    if devices is None:
        devices = jax.devices()
    sizes = dict(axes)
    order = [a for a in AXIS_NAMES if a in sizes] + [
        a for a in sizes if a not in AXIS_NAMES
    ]
    shape = [sizes[a] for a in order]
    n = int(onp.prod(shape)) if shape else 1
    if n > len(devices):
        raise ValueError(
            f"mesh {sizes} needs {n} devices, only {len(devices)} available"
        )
    dev_array = onp.array(devices[:n]).reshape(shape)
    return Mesh(dev_array, tuple(order))


def auto_mesh(n_devices: Optional[int] = None, *, dp: Optional[int] = None,
              tp: int = 1, sp: int = 1, ep: int = 1, pp: int = 1,
              fsdp: int = 1) -> Mesh:
    """Mesh over the first ``n_devices`` with ``dp`` filling the remainder."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    fixed = tp * sp * ep * pp * fsdp
    if dp is None:
        if n_devices % fixed:
            raise ValueError(f"{n_devices} devices not divisible by {fixed}")
        dp = n_devices // fixed
    elif dp * fixed != n_devices:
        raise ValueError(
            f"dp={dp} * (tp*sp*ep*pp*fsdp={fixed}) != n_devices={n_devices}; "
            f"would strand {n_devices - dp * fixed} devices")
    axes = {}
    for name, size in (("pp", pp), ("dp", dp), ("fsdp", fsdp), ("ep", ep),
                       ("sp", sp), ("tp", tp)):
        if size > 1 or name == "dp":
            axes[name] = size
    return make_mesh(axes, devices[:n_devices])


def current_mesh() -> Optional[Mesh]:
    return _CURRENT[0]


def set_mesh(mesh: Optional[Mesh]):
    _CURRENT[0] = mesh


class mesh_scope:
    """``with mesh_scope(mesh): ...`` — also enters the jax mesh context so
    bare ``pjit``/sharding-constraint calls resolve axis names."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._prev: Optional[Mesh] = None

    def __enter__(self):
        self._prev = _CURRENT[0]
        _CURRENT[0] = self.mesh
        self._ctx = self.mesh.__enter__()
        return self.mesh

    def __exit__(self, *exc):
        self.mesh.__exit__(*exc)
        _CURRENT[0] = self._prev
        return False
