"""Fault tolerance: async checkpointing, failure detection, elastic resume.

The reference has essentially nothing here — process death kills the job;
the only robustness is exception propagation across the async engine and a
shutdown barrier (SURVEY §5: ``include/mxnet/kvstore.h:362``
barrier_before_exit, ``src/engine/threaded_engine.h:64`` ExceptionRef).
On TPU pods, preemption and host failure are routine, so this subsystem
EXCEEDS reference parity by design:

- :class:`CheckpointManager` — atomic, optionally async (background
  thread) checkpoints of an arbitrary pytree (params / optimizer state /
  step), with retention, written per-host so sharded ``jax.Array`` leaves
  save only their addressable shards.
- :class:`HeartbeatMonitor` — file-based liveness for launcher-spawned
  multi-process jobs (``tools/launch.py``): each rank beats; any rank (or
  an external supervisor) can list dead ranks.
- :func:`run_elastic` — step-loop wrapper: checkpoint every N steps,
  trap worker failure, restore the latest checkpoint, and continue — the
  train loop's state after a mid-run crash equals the uninterrupted run's.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import queue
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import config as _config
from .. import engine as _engine
from .. import faults as _faults
from .. import preemption as _preemption
from .. import telemetry as _telemetry
from ..log import get_logger

__all__ = ["CheckpointManager", "HeartbeatMonitor", "run_elastic",
           "AnomalyDetected", "DigestMismatch", "nonfinite_anomaly"]

_LOG = get_logger("mxnet_tpu.elastic")

# recovery observability (ISSUE 11 / ROADMAP 4c: a recovery-time METRIC,
# not a guess): set/incremented by run_elastic on every restore
_RECOVERY_S = _telemetry.counter(
    "elastic.recovery_s",
    "seconds the most recent run_elastic checkpoint restore took "
    "(degradation walk + load + re-placement via restore(like=))",
    kind="time")
_STEPS_REPLAYED = _telemetry.counter(
    "elastic.steps_replayed",
    "train steps re-executed after restores (crash step index minus "
    "restored step; a graceful preemption drain replays 0)")
_RESTORES = _telemetry.counter(
    "elastic.restores", "successful run_elastic checkpoint restores "
    "(startup resumes + in-process crash recoveries)")
_DIGEST_MISMATCHES = _telemetry.counter(
    "checkpoint.digest_mismatches",
    "checkpoint payloads whose sha256 content digest disagreed with "
    "their sidecar (bit rot / torn replace); the step degrades whole "
    "to the previous complete one")


class AnomalyDetected(RuntimeError):
    """A step produced a state the anomaly detector rejected (e.g. a
    non-finite loss); run_elastic rolls back to the last checkpoint under
    the same ``max_restarts`` budget."""


class DigestMismatch(ValueError):
    """A checkpoint payload's sha256 disagrees with its ``.sha256``
    sidecar — a silent bit-flip that would still unpickle.  Restore
    auto-selection degrades to the previous complete step exactly like
    a truncated pickle; an explicit ``step=`` raises this."""


# What a truncated/corrupt checkpoint file can raise while loading:
# pickle/EOF for torn bytes, OSError for an unreadable file, Value/Index/
# Key for a payload whose structure no longer matches (DigestMismatch is
# a ValueError: content-digest failures degrade the same way), plus
# injected faults (site checkpoint.restore).  Anything else is a real
# bug and propagates.
_RESTORE_ERRORS = (pickle.UnpicklingError, EOFError, OSError, ValueError,
                   IndexError, KeyError, _faults.FaultInjected)


def _tree_cow(tree):
    """Copy-on-write device snapshot: every jax leaf gets an ON-DEVICE
    copy (a cheap async HBM copy enqueued on the dispatch stream — XLA
    orders it BEFORE any later donated program overwrites the source
    buffer).  The background writer then reads the copies to host at
    leisure, so the live tree — including donated compiled-step buffers
    — is never touched after save() returns.  Returns ``None`` when a
    leaf cannot be COW-copied (non-fully-addressable multihost shards
    need the original array's shard structure -> synchronous snapshot).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            if not leaf.is_fully_addressable:
                return None
            out.append(jnp.copy(leaf))
        else:
            out.append(onp.array(leaf, copy=True))
    return jax.tree_util.tree_unflatten(treedef, out)


def _tree_to_host(tree):
    """Device -> host: each process materializes only its addressable
    shards (multihost-safe; a fully-replicated single-host array is just
    the array)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    host_leaves = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            shards = [(s.index, onp.asarray(s.data))
                      for s in leaf.addressable_shards]
            host_leaves.append(("shards", leaf.shape, shards))
        else:
            # copy=True: onp.asarray on a host numpy leaf would alias the
            # live buffer and let post-save mutation leak into the write
            host_leaves.append(("full", None, onp.array(leaf, copy=True)))
    return treedef, host_leaves


class CheckpointManager:
    """Atomic, retained, optionally asynchronous checkpoints.

    Layout: ``<directory>/ckpt-<step>.pkl`` (one file per host via a
    ``-h<process_index>`` suffix under multi-controller).  Writes go to a
    temp file then ``os.replace`` — a crash mid-save can never corrupt the
    latest checkpoint (same discipline as the native .so build).
    """

    def __init__(self, directory: str, keep: int = 3, async_save: bool = True):
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        os.makedirs(directory, exist_ok=True)
        self._clean_stale_tmp()
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._closed = False
        # snapshot-path observability: "async" = COW device snapshot
        # read to host by the writer thread; "sync" = host copy on the
        # caller thread (block=True, NaiveEngine, or multihost shards)
        self.snapshot_stats = {"async": 0, "sync": 0}
        if async_save:
            self._worker = threading.Thread(target=self._writer, daemon=True)
            self._worker.start()
        _engine.register_drainable(self)

    def _clean_stale_tmp(self) -> None:
        """Remove temp files left by DEAD writers (a SIGKILL mid-write
        leaks ``<path>.<pid>.tmp``; the atomic-replace discipline means
        they are never part of any checkpoint).  Live pids — another
        host process sharing the directory — are left alone, so the
        recovery-budget gate can assert 0 leaked temp files after a
        kill."""
        for f in os.listdir(self.directory):
            m = re.match(r".*\.(\d+)\.tmp$", f)
            if not m or int(m.group(1)) == os.getpid():
                continue
            try:
                os.kill(int(m.group(1)), 0)
            except ProcessLookupError:
                try:
                    os.remove(os.path.join(self.directory, f))
                    _LOG.warning("removed stale checkpoint temp file %s "
                                 "(writer pid %s is dead)", f, m.group(1))
                except OSError:
                    pass
            except OSError:
                pass                      # alive (or not ours): keep

    # -- paths ----------------------------------------------------------
    def _suffix(self) -> str:
        idx = jax.process_index() if jax.process_count() > 1 else 0
        return f"-h{idx}" if jax.process_count() > 1 else ""

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step}{self._suffix()}.pkl")

    def all_steps(self) -> List[int]:
        """Steps with a file from ANY host (includes partially-saved steps;
        use :meth:`complete_steps` when picking a restore point)."""
        pat = re.compile(r"ckpt-(\d+)(?:-h\d+)?\.pkl$")
        steps = set()
        for f in os.listdir(self.directory):
            m = pat.match(f)
            if m:
                steps.add(int(m.group(1)))
        return sorted(steps)

    def _present_hosts(self, step: int) -> set:
        """Process indices whose file for ``step`` has landed (a file with
        no -h suffix counts as host 0)."""
        pat = re.compile(rf"ckpt-{step}(?:-h(\d+))?\.pkl$")
        hosts = set()
        for f in os.listdir(self.directory):
            m = pat.match(f)
            if m:
                hosts.add(int(m.group(1) or 0))
        return hosts

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step}.meta")

    def _saved_world(self, step: int) -> int:
        """World size recorded WHEN the step was saved.  After an elastic
        restart with more hosts, comparing against the *current*
        ``process_count`` would leave every old step forever 'incomplete'
        (and GC would then never delete anything).  Falls back to the
        current world for legacy checkpoints without a meta file."""
        try:
            with open(self._meta_path(step)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return jax.process_count()

    def complete_steps(self) -> List[int]:
        """Steps whose per-host files exist for every process OF THE WORLD
        THAT SAVED THEM.  Hosts save asynchronously, so a crash can leave
        the newest step with only some hosts' files; restoring it would
        raise on the lagging hosts or let hosts silently resume from
        different steps.  Restore therefore intersects across hosts and
        only offers steps every saving host finished.
        """
        return [s for s in self.all_steps()
                if len(self._present_hosts(s)) >= self._saved_world(s)]

    def latest_step(self) -> Optional[int]:
        """Newest step complete on every host (the only safe restore
        point under multi-controller; equals the newest file single-host).
        """
        steps = self.complete_steps()
        return steps[-1] if steps else None

    # -- save -----------------------------------------------------------
    def save(self, step: int, tree: Any, block: bool = False) -> None:
        """Snapshot the tree and write it, async by default.

        Async path (the pipeline engine stage): the caller thread only
        enqueues an ON-DEVICE copy of every jax leaf (:func:`_tree_cow`
        — the copy-on-write guard: a later step donating/overwriting the
        live buffers can never corrupt the snapshot, because the
        snapshot reads the copies, and XLA orders the copy before the
        overwrite).  The device->host transfer AND the pickle+write both
        happen on the background writer (site ``checkpoint.async``), so
        a checkpoint costs the train loop one async HBM copy instead of
        a stop-the-world host transfer.  ``block=True``,
        ``MXNET_ENGINE_TYPE=NaiveEngine``, or non-fully-addressable
        (multihost-sharded) leaves fall back to the synchronous host
        snapshot on the caller thread."""
        if self._closed:
            raise RuntimeError(
                "CheckpointManager is closed; save() would be silently "
                "dropped (no writer thread remains)")
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"previous async checkpoint failed: {err}")
        if self.async_save and not block and not _engine.is_naive():
            try:
                cow = _tree_cow(tree)
            except Exception:       # exotic leaves: sync snapshot below
                cow = None
            if cow is not None:
                self.snapshot_stats["async"] += 1
                self._q.put(("cow", step, cow))
                return
        self.snapshot_stats["sync"] += 1
        payload = _tree_to_host(tree)
        if self.async_save and not block:
            self._q.put(("host", step, payload))
        else:
            self._write(step, payload)

    def _writer(self):
        while True:
            item = self._q.get()
            if item is None:
                # balance the close() sentinel: an unmatched get would
                # leave unfinished_tasks at 1 forever and wedge every
                # later _q.join() (engine.waitall drains us weakly even
                # after close)
                self._q.task_done()
                return
            kind, step, data = item
            try:
                if kind == "cow":
                    # background device->host snapshot of the COW copies
                    _faults.inject("checkpoint.async")
                    data = _tree_to_host(data)
                self._write(step, data)
            except BaseException as e:  # surfaced on the next save()
                self._error = e
            finally:
                self._q.task_done()

    def _write(self, step: int, payload) -> None:
        """Write one checkpoint under the shared retry policy (site
        ``checkpoint.write``): a transient filesystem failure (network FS
        flap, preempted host) re-runs the whole atomic write with
        backoff; the temp-then-replace discipline makes a replay
        harmless."""
        _faults.retry_call(self._write_once, step, payload,
                           site="checkpoint.write")

    def _write_once(self, step: int, payload) -> None:
        path = self._path(step)
        tmp = f"{path}.{os.getpid()}.tmp"
        dtmp = f"{path}.sha256.{os.getpid()}.tmp"
        data = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            # content-digest sidecar, replaced BEFORE the payload: a
            # crash between the two replaces pairs the new digest with
            # the old payload -> restore sees a mismatch and degrades
            # whole-step, exactly like a truncated pickle.  The digest
            # is what catches the silent bit-flip that still unpickles.
            with open(dtmp, "w") as f:
                f.write(hashlib.sha256(data).hexdigest())
            os.replace(dtmp, f"{path}.sha256")
            os.replace(tmp, path)
        except BaseException:
            # never leave a partial temp file for a retry (or a later
            # incarnation of this pid) to trip over
            for t in (tmp, dtmp):
                try:
                    os.remove(t)
                except OSError:
                    pass
            raise
        # record the saving world size (every host writes identical
        # content; atomic replace makes the race harmless)
        meta_tmp = f"{self._meta_path(step)}.{os.getpid()}.tmp"
        with open(meta_tmp, "w") as f:
            f.write(str(jax.process_count()))
        os.replace(meta_tmp, self._meta_path(step))
        self._gc()

    def _gc(self) -> None:
        if self.keep <= 0:
            return
        # retain the last ``keep`` COMPLETE steps, plus anything newer (its
        # files may still be landing on other hosts) — counting a partial
        # step toward ``keep`` could evict the only restorable checkpoint
        protected = set(self.complete_steps()[-self.keep:])
        newest = max(protected) if protected else -1
        for s in self.all_steps():
            if s in protected or s > newest:
                continue
            for f in os.listdir(self.directory):
                if re.match(rf"ckpt-{s}(?:-h\d+)?\.pkl(?:\.sha256)?$", f) \
                        or f == f"ckpt-{s}.meta":
                    try:
                        os.remove(os.path.join(self.directory, f))
                    except OSError:
                        pass

    def wait(self) -> None:
        """Block until queued async saves (snapshots AND writes) hit
        disk (call before exit)."""
        if self.async_save:
            self._q.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"async checkpoint failed: {err}")

    def drain(self) -> None:
        """engine.waitall() hook: flush queued snapshots/writes; an
        asynchronously-absorbed failure surfaces here, like the
        reference engine re-raising a captured op exception at the wait
        point.  A closed manager has nothing in flight (close() joins
        the writer) — no-op instead of waiting on a dead thread."""
        if self._closed:
            return
        self.wait()

    # -- restore --------------------------------------------------------
    def _step_files(self, step: int) -> List[str]:
        """Files for ``step`` from hosts INSIDE the world that saved it.
        A crashed larger-world incarnation of the same step number can
        leave stale ``-h<big>`` files behind (GC protects the whole step);
        merging those would overwrite fresh rows with pre-crash values."""
        pat = re.compile(rf"ckpt-{step}(?:-h(\d+))?\.pkl$")
        world = self._saved_world(step)
        out = []
        for f in os.listdir(self.directory):
            m = pat.match(f)
            if m and int(m.group(1) or 0) < world:
                out.append(os.path.join(self.directory, f))
        return sorted(out)

    def restore(self, step: Optional[int] = None, like: Any = None):
        """Load a checkpoint (latest by default).  With ``like`` (a pytree
        of arrays carrying shardings), sharded leaves are re-placed with
        their original sharding via ``jax.device_put``.

        Graceful degradation: when ``step`` is NOT given and the newest
        complete step turns out to be truncated/corrupt on disk (crash
        mid-replace survived by a broken network-FS write, bit rot), the
        WHOLE step is abandoned and the previous complete step is tried —
        a fault event is recorded, and hosts can never silently mix
        leaves across steps, because degradation always moves to an older
        step in its entirety.  An EXPLICIT ``step`` never falls back: the
        caller asked for that step, so corruption raises.
        """
        if step is not None:
            return self._restore_step(step, like), step
        candidates = self.complete_steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[BaseException] = None
        for s in reversed(candidates):
            try:
                return self._restore_step(s, like), s
            except _RESTORE_ERRORS as e:
                last_err = e
                _faults.record_event("checkpoint.restore", "degrade",
                                     error=e, step=s)
                _LOG.warning(
                    "checkpoint step %d unrestorable (%r); degrading to "
                    "the previous complete step", s, e)
        raise RuntimeError(
            f"no restorable checkpoint in {self.directory}: every "
            f"complete step {candidates} failed to load "
            f"(last error: {last_err!r})") from last_err

    def _load_verified(self, path: str):
        """Read + unpickle one checkpoint file, verifying its sha256
        content digest when a ``.sha256`` sidecar exists (legacy
        checkpoints without one load unverified).  A mismatch raises
        :class:`DigestMismatch` — the silent bit-flip that would still
        unpickle degrades exactly like a truncated pickle."""
        with open(path, "rb") as f:
            data = f.read()
        dpath = f"{path}.sha256"
        if os.path.exists(dpath):
            with open(dpath) as f:
                want = f.read().strip()
            got = hashlib.sha256(data).hexdigest()
            if got != want:
                _DIGEST_MISMATCHES.inc()
                _faults.record_event(
                    "checkpoint.restore", "digest_mismatch",
                    file=os.path.basename(path))
                raise DigestMismatch(
                    f"checkpoint {path} content digest mismatch "
                    f"(sha256 {got[:12]}… != recorded {want[:12]}…): "
                    "bit rot or torn write survived the unpickle check")
        return pickle.loads(data)

    def _restore_step(self, step: int, like: Any = None):
        """Load one specific step (one attempt, site
        ``checkpoint.restore``)."""
        _faults.inject("checkpoint.restore")
        paths = self._step_files(step)
        if not paths:
            raise FileNotFoundError(
                f"no files for step {step} in {self.directory}")
        own = self._path(step)
        primary = own if own in paths else paths[0]
        treedef, host_leaves = self._load_verified(primary)
        # merge shard payloads from the other saving hosts' files
        needs_merge = any(kind == "shards" for (kind, _s, _d) in host_leaves)
        if needs_merge:
            for p in paths:
                if p == primary:
                    continue
                _td, other = self._load_verified(p)
                for mine, theirs in zip(host_leaves, other):
                    if mine[0] == "shards" and theirs[0] == "shards":
                        mine[2].extend(theirs[2])
        like_leaves = (jax.tree_util.tree_flatten(like)[0]
                       if like is not None else [None] * len(host_leaves))
        if like is not None and len(like_leaves) != len(host_leaves):
            # a silent zip-truncation here would re-place only a prefix
            # of the leaves; raise the mismatch loudly (auto-selection
            # may still degrade to an older structurally-matching step)
            raise ValueError(
                f"checkpoint step {step} holds {len(host_leaves)} "
                f"leaves but like= carries {len(like_leaves)} — the "
                "live state tree's structure differs from the saved one")
        leaves = []
        for (kind, shape, data), ref in zip(host_leaves, like_leaves):
            if kind == "shards":
                full = onp.zeros(shape, data[0][1].dtype)
                for index, shard in data:
                    full[index] = shard
                arr = full
            else:
                arr = data
            if ref is not None and isinstance(ref, jax.Array):
                leaves.append(jax.device_put(arr, ref.sharding))
            else:
                leaves.append(arr)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def close(self):
        self._closed = True
        if self._worker is not None:
            self._q.put(None)
            self._worker.join(timeout=30)
            self._worker = None


class HeartbeatMonitor:
    """Liveness over a beat table: each member stamps a beat; a member
    whose newest beat is older than ``timeout`` is dead.  The analog of
    ps-lite's node heartbeats, which the reference never surfaced to
    users (SURVEY §5).  Two storage modes behind one interface:

    - **shared directory** (``directory=`` set): file-mtime beats
      (``<dir>/rank-<r>.hb``, touched every ``interval`` by the
      :meth:`start` thread) — works with the multi-process local/ssh
      launcher; this is the kvstore-barrier attachment.
    - **in-memory** (``directory=None``): a plain ``{key: monotonic}``
      table for CO-HOSTED members inside one process — the serving
      router's engine heartbeats, where a beat is stamped PER DISPATCH
      (``beat(key)``) rather than by a timer, so a wedged replica is
      one whose dispatch is outstanding with no beat for ``timeout``.

    Keys (``rank``) may be ints (launcher ranks) or strings (engine
    replica names)."""

    def __init__(self, directory: Optional[str] = None, rank=0,
                 interval: float = 2.0, timeout: float = 10.0):
        self.directory = directory
        self.rank = rank
        self.interval = interval
        self.timeout = timeout
        self._beats: Dict[Any, float] = {}
        self._beats_lock = threading.Lock()
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _path(self, rank) -> str:
        return os.path.join(self.directory, f"rank-{rank}.hb")

    def beat(self, rank=None) -> None:
        """Stamp a beat for ``rank`` (default: our own).  In-memory
        monitors stamp per EVENT (the router calls this per dispatch
        completion); directory monitors touch the rank's mtime file."""
        rank = self.rank if rank is None else rank
        if self.directory is None:
            with self._beats_lock:
                self._beats[rank] = time.monotonic()
            return
        path = self._path(rank)
        with open(path, "a"):
            os.utime(path, None)

    def start(self) -> "HeartbeatMonitor":
        self.beat()
        # graftlint: daemon-ok(filesystem mtime heartbeat only — no
        # queued work for waitall to miss; stop() joins it)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def ranks(self) -> List:
        if self.directory is None:
            with self._beats_lock:
                return sorted(self._beats, key=str)
        out = []
        for f in os.listdir(self.directory):
            m = re.match(r"rank-(\d+)\.hb$", f)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def age(self, rank=None, now: Optional[float] = None) -> Optional[float]:
        """Seconds since ``rank``'s newest beat (None = never beat).
        In-memory mode measures against ``time.monotonic()``."""
        rank = self.rank if rank is None else rank
        if self.directory is None:
            with self._beats_lock:
                t = self._beats.get(rank)
            if t is None:
                return None
            return (time.monotonic() if now is None else now) - t
        try:
            t = os.path.getmtime(self._path(rank))
        except OSError:
            return None
        return (time.time() if now is None else now) - t

    def dead_ranks(self, now: Optional[float] = None) -> List:
        dead = []
        for r in self.ranks():
            a = self.age(r, now=now)
            if a is None or a > self.timeout:
                dead.append(r)
        return dead


def nonfinite_anomaly(*keys: str, every: int = 1) -> Callable[[Any], bool]:
    """Anomaly detector factory for :func:`run_elastic`: flags a state
    whose ``state[key]`` holds any non-finite value (NaN/Inf loss — the
    classic silent-divergence failure a crash handler never sees).

    ``every`` is the evaluation cadence :func:`run_elastic` honors (the
    ``anomaly_fn.every`` contract): each evaluation is a blocking host
    read of the named leaves, so a cadence > 1 keeps non-sentinel steps
    at 0 host syncs.  Default 1 preserves the per-step behavior; the
    windowed generalization lives in :class:`mxnet_tpu.sentinel.
    Sentinel`, whose digest reads are deferred AND cadenced."""
    def _check(state) -> bool:
        for k in keys:
            if not bool(onp.all(onp.isfinite(onp.asarray(state[k])))):
                return True
        return False
    _check.every = int(every)
    return _check


def _restore_counted(ckpt: CheckpointManager, state: Any):
    """One observed restore: retried under the shared policy (site
    ``elastic.restore`` — a network-FS flap while reading is as routine
    as one while writing), timed into ``elastic.recovery_s``, counted
    in ``elastic.restores``."""
    t0 = time.monotonic()
    restored, step = _faults.retry_call(ckpt.restore, like=state,
                                        site="elastic.restore")
    _RECOVERY_S.set(time.monotonic() - t0)
    _RESTORES.inc()
    return restored, step


def run_elastic(step_fn: Callable, state: Any, inputs: Iterable,
                ckpt: CheckpointManager, save_every: int = 10,
                max_restarts: int = 3, on_restart: Optional[Callable] = None,
                restart_backoff: Optional[float] = None,
                anomaly_fn: Optional[Callable[[Any], bool]] = None,
                on_restore: Optional[Callable[[Any, int], Any]] = None,
                preemption: bool = False,
                kvstore: Any = None):
    """Run ``state = step_fn(state, batch)`` over ``inputs`` with periodic
    checkpoints; on an exception, restore the latest checkpoint, skip
    already-consumed steps, and continue (up to ``max_restarts``).

    ``inputs`` must be re-iterable so skipped prefixes replay
    deterministically: anything already supporting ``len`` + indexing (a
    list, a ``range``, a dataset view) is consumed IN PLACE — no
    materializing copy, so an epoch of device-sized batches no longer
    doubles host RSS — while a bare iterator/generator is listed once.
    Returns (final_state, steps_run, restarts).

    Hardening (docs/ROBUSTNESS.md):

    - ``restart_backoff`` (default ``MXNET_ELASTIC_BACKOFF``): exponential
      delay ``min(backoff * 2**(restart-1), MXNET_RETRY_BACKOFF_MAX)``
      before each restore — a crashing dependency (storage, a flapping
      peer) gets time to recover instead of being hammered.
    - ``anomaly_fn(state) -> bool`` (e.g. ``nonfinite_anomaly("loss")``
      or a :class:`mxnet_tpu.sentinel.Sentinel`): a True verdict after a
      step raises :class:`AnomalyDetected`, which rolls back to the last
      checkpoint under the SAME ``max_restarts`` budget — a
      deterministically diverging run still terminates.  An
      ``anomaly_fn.every`` attribute sets the evaluation cadence
      (detectors whose evaluation costs a host sync stop paying it on
      every step — the sentinel-cadence routing); an ``anomaly_fn.flush()``
      method, when present, is called immediately BEFORE every
      checkpoint save and its verdict raises the same way — so a
      sentinel-rejected state is never checkpointed and every rollback
      target is attested.
    - ``on_restore(state, step)`` runs after EVERY successful restore
      (the startup resume included): push the restored pytree back into
      live objects — net parameters, optimizer state — before stepping
      resumes; a non-``None`` return replaces the loop state.  This is
      what lets the loop drive a compiled SPMD ``TrainStep`` whose
      params live in the Trainer, not the state tree.
    - ``preemption=True`` installs the :mod:`mxnet_tpu.preemption`
      SIGTERM/SIGINT handler; whenever a handler is installed (here or
      by the caller) the loop registers the final-save drain hook — a
      notice drains the async queues and force-saves the LAST COMPLETED
      step blocking, so the graceful path replays 0 steps — and the
      loop itself exits via :class:`preemption.Preempted` when it
      observes the draining flag (the in-process/cooperative path).
    - ``kvstore``: with a barrier deadline configured
      (``MXNET_BARRIER_TIMEOUT`` > 0) and no monitor attached yet, a
      :class:`HeartbeatMonitor` is created under
      ``<ckpt.directory>/heartbeats``, started, and attached
      automatically — a deadline breach names suspected-dead ranks
      instead of reporting "no HeartbeatMonitor attached".
    - each iteration passes the ``elastic.step`` injection site and each
      restore the ``elastic.restore`` site, so crash recovery is
      testable without a real preemption; restores are timed into the
      ``elastic.recovery_s`` / ``elastic.steps_replayed`` counters and
      restarts emit ``restart`` events stamped with step indices.
    """
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    if restart_backoff is None:
        restart_backoff = _config.get("MXNET_ELASTIC_BACKOFF")
    if not (hasattr(inputs, "__len__") and hasattr(inputs, "__getitem__")):
        inputs = list(inputs)
    n = len(inputs)
    hb: Optional[HeartbeatMonitor] = None
    if kvstore is not None and hasattr(kvstore, "attach_heartbeat") \
            and getattr(kvstore, "_heartbeat", None) is None \
            and _config.get("MXNET_BARRIER_TIMEOUT") > 0:
        hb = HeartbeatMonitor(os.path.join(ckpt.directory, "heartbeats"),
                              rank=jax.process_index()).start()
        kvstore.attach_heartbeat(hb)
    if preemption:
        _preemption.install()
    # live loop cell the preemption drain hook reads: a SIGTERM
    # interrupting step i finds (i, state-before-step-i) here — the
    # final blocking save checkpoints the last COMPLETED step
    loop = {"state": state, "i": 0}
    # anomaly-detector cadence (the sentinel routing): a plain function
    # evaluates every step (the PR-2 behavior); a detector carrying
    # .every — nonfinite_anomaly(every=N), sentinel.Sentinel — is only
    # consulted on its cadence, so non-sentinel steps pay 0 host syncs.
    # .flush(), when present, runs before every save (verdict-gates the
    # checkpoint so a tainted state is never written).
    anomaly_every = max(1, int(getattr(anomaly_fn, "every", 1) or 1))
    anomaly_flush = getattr(anomaly_fn, "flush", None)
    hook = None
    if preemption or _preemption.installed():
        def _final_save():
            ckpt.save(loop["i"], loop["state"], block=True)
        hook = _preemption.on_drain(_final_save)
    try:
        start = 0
        if ckpt.latest_step() is not None:
            state, start = _restore_counted(ckpt, state)
            _telemetry.event("restart", "elastic", step=start,
                             phase="startup_restore")
            if on_restore is not None:
                ns = on_restore(state, start)
                if ns is not None:
                    state = ns
        else:
            # step-0 anchor: a crash before the first periodic save
            # restores pristine state instead of continuing from a
            # corrupted one
            ckpt.save(0, state, block=True)
        restarts = 0
        i = start
        loop["state"], loop["i"] = state, i
        while i < n:
            if _preemption.draining():
                break                      # cooperative graceful drain
            try:
                _faults.inject("elastic.step")
                new_state = step_fn(state, inputs[i])
                if anomaly_fn is not None \
                        and (i + 1) % anomaly_every == 0 \
                        and anomaly_fn(new_state):
                    raise AnomalyDetected(
                        f"anomaly detected in the state after step {i}")
                state = new_state
                i += 1
                loop["state"], loop["i"] = state, i
                if i % save_every == 0 or i == n:
                    if anomaly_flush is not None and anomaly_flush():
                        raise AnomalyDetected(
                            f"sentinel verdict before the save at step "
                            f"{i}; the tainted state was NOT "
                            "checkpointed")
                    ckpt.save(i, state)
            except Exception as e:
                restarts += 1
                _faults.record_event("elastic.restart", "restart", error=e,
                                     step=i, restart=restarts)
                if restarts > max_restarts:
                    ckpt.wait()
                    raise
                _LOG.warning("elastic restart %d/%d at step %d: %r",
                             restarts, max_restarts, i, e)
                if on_restart is not None:
                    on_restart(restarts)
                ckpt.wait()
                if restart_backoff > 0:
                    _faults._sleep(min(
                        restart_backoff * (2 ** (restarts - 1)),
                        _config.get("MXNET_RETRY_BACKOFF_MAX")))
                prev_i = i
                state, i = _restore_counted(ckpt, state)
                _STEPS_REPLAYED.inc(max(0, prev_i - i))
                _telemetry.event("restart", "elastic", step=i,
                                 restart=restarts,
                                 replay=max(0, prev_i - i))
                if on_restore is not None:
                    ns = on_restore(state, i)
                    if ns is not None:
                        state = ns
                loop["state"], loop["i"] = state, i
        if _preemption.draining() and i < n:
            # drain observed between steps (programmatic notice, stubbed
            # exit, or a handler on another thread): flush the async
            # queues, force the final blocking save, and exit with the
            # distinguished code.  Saving the same step the signal
            # handler's drain hook saved is idempotent.
            _engine.waitall()
            ckpt.save(i, state, block=True)
            ckpt.wait()
            _telemetry.event("drain", "elastic", step=i)
            raise _preemption.Preempted(_preemption.exit_code())
        ckpt.wait()
        return state, i, restarts
    finally:
        if hook is not None:
            _preemption.remove_drain_hook(hook)
        if hb is not None:
            hb.stop()
