"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

No MoE exists in the reference (SURVEY.md §5); this is forward-looking
capability required for the TPU build's first-class distributed story.
Design follows the standard TPU recipe: top-k gating with capacity,
einsum-based dense dispatch/combine (MXU-friendly, no dynamic shapes), expert
weights sharded over ``ep`` so the dispatch einsum lowers to an all-to-all
over ICI.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["top_k_gating", "moe_layer", "aux_scope", "record_aux",
           "MoEBlock"]


# ---------------------------------------------------------------------------
# load-balance aux-loss plumbing (the Trainer loss path)
# ---------------------------------------------------------------------------
# A gluon forward has no side channel for the gating aux loss; this
# thread-local scope is it.  cached_step.TrainStep opens the scope around
# the traced forward (compiled AND eager paths) and folds
# MXNET_MOE_AUX_WEIGHT * sum(recorded) into the differentiated loss
# heads, so the load-balance loss reaches the optimizer without touching
# the user's loss_fn signature.

_AUX = threading.local()


@contextlib.contextmanager
def aux_scope():
    """Collect aux losses recorded by MoE blocks during the enclosed
    forward.  Yields the (mutable) list; nesting restores the outer
    scope on exit."""
    prev = getattr(_AUX, "lst", None)
    _AUX.lst = []
    try:
        yield _AUX.lst
    finally:
        _AUX.lst = prev


def record_aux(aux) -> bool:
    """Record one load-balance aux-loss value into the active scope (a
    no-op returning False when no scope is open — e.g. pure-jax callers
    like models/transformer_lm.py that fold the aux themselves)."""
    lst = getattr(_AUX, "lst", None)
    if lst is None:
        return False
    lst.append(aux)
    return True


def top_k_gating(x, gate_w, *, num_experts: int, k: int = 2,
                 capacity_factor: float = 1.25,
                 capacity: Optional[int] = None):
    """Compute dispatch/combine tensors for top-k routing.

    x: [G, S, M] (groups=batch shards, tokens, model dim)
    gate_w: [M, E]
    Returns (dispatch [G, S, E, C] bool-ish float, combine [G, S, E, C],
    aux_loss scalar).  Static shapes throughout: tokens over capacity C are
    dropped (their combine weights are zero), the standard TPU trick to keep
    XLA shapes static (vs the reference's dynamic-shape boolean_mask ops).
    """
    G, S, M = x.shape
    E = num_experts
    if capacity is None:
        capacity = max(1, int(capacity_factor * S * k / E))
    C = capacity

    logits = jnp.einsum("gsm,me->gse", x, gate_w)
    probs = jax.nn.softmax(logits, axis=-1)

    # load-balancing auxiliary loss (Shazeer et al.): mean prob * mean assignment
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=1)                               # [G, E]
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=x.dtype), axis=1)
    aux_loss = jnp.mean(jnp.sum(me * ce, axis=-1)) * E

    dispatch = jnp.zeros((G, S, E, C), dtype=x.dtype)
    combine = jnp.zeros((G, S, E, C), dtype=x.dtype)
    # running per-expert position counters, updated as we take each of k choices
    position_in_expert = jnp.zeros((G, E), dtype=jnp.int32)
    p = probs
    for _ in range(k):
        idx = jnp.argmax(p, axis=-1)                            # [G, S]
        gate = jnp.take_along_axis(p, idx[..., None], axis=-1)[..., 0]
        p = p * (1.0 - jax.nn.one_hot(idx, E, dtype=p.dtype))   # mask chosen
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)        # [G, S, E]
        # position of each token within its chosen expert's queue
        pos = position_in_expert[:, None, :] + jnp.cumsum(onehot, axis=1) - onehot
        pos_tok = jnp.sum(pos * onehot, axis=-1)                # [G, S]
        position_in_expert = position_in_expert + jnp.sum(onehot, axis=1)
        keep = (pos_tok < C).astype(x.dtype)                    # capacity drop
        gate = gate * keep
        pos_oh = jax.nn.one_hot(jnp.minimum(pos_tok, C - 1), C, dtype=x.dtype)
        contrib = onehot.astype(x.dtype)[..., None] * pos_oh[:, :, None, :]
        dispatch = dispatch + contrib * keep[..., None, None]
        combine = combine + contrib * gate[..., None, None]
    return dispatch, combine, aux_loss


def moe_layer(x, gate_w, w_in, w_out, *, k: int = 2,
              capacity_factor: float = 1.25, capacity: Optional[int] = None,
              activation=jax.nn.gelu) -> Tuple[jax.Array, jax.Array]:
    """Dense-dispatch MoE FFN.

    x: [G, S, M]; gate_w: [M, E]; w_in: [E, M, H]; w_out: [E, H, M].
    Shard w_in/w_out over 'ep' on dim 0 (ShardingPlan rule `expert.*` /
    name-aware ``spmd.param_spec``) and XLA turns the dispatch einsums
    into all-to-alls over the ep axis; the expert-dim intermediates carry
    mesh-agnostic ``sharding.constraint(P('ep', 'dp'))`` annotations so
    the partitioner keeps per-expert compute on the expert's devices
    (axes absent from the ambient mesh legalize away silently).
    Returns (output [G, S, M], aux_loss).
    """
    from .sharding import PartitionSpec as _P, constraint as _constraint

    E = gate_w.shape[-1]
    dispatch, combine, aux = top_k_gating(
        x, gate_w, num_experts=E, k=k, capacity_factor=capacity_factor,
        capacity=capacity)
    # [G,S,E,C] x [G,S,M] -> expert inputs [E, G, C, M]
    ep_spec = _P("ep", "dp", None, None)
    expert_in = _constraint(
        jnp.einsum("gsec,gsm->egcm", dispatch, x), ep_spec)
    h = _constraint(
        activation(jnp.einsum("egcm,emh->egch", expert_in, w_in)), ep_spec)
    expert_out = _constraint(
        jnp.einsum("egch,ehm->egcm", h, w_out), ep_spec)
    out = jnp.einsum("gsec,egcm->gsm", combine, expert_out)
    return out, aux


# ---------------------------------------------------------------------------
# Gluon adapter: expert-parallel MoE FFN as a trainable Block
# ---------------------------------------------------------------------------

_MOE_BLOCK_CLS = None


def _moe_block_cls():
    """Build the MoEBlock class lazily: gluon imports here (not at module
    import) keep ``mxnet_tpu.parallel`` free of an import cycle through
    the gluon package."""
    global _MOE_BLOCK_CLS
    if _MOE_BLOCK_CLS is not None:
        return _MOE_BLOCK_CLS

    from .. import autograd as _ag
    from ..context import current_context
    from ..gluon.block import Block, jax_bridge
    from ..gluon.parameter import Parameter
    from ..ndarray import NDArray
    from ..ndarray.ndarray import _wrap

    class _Holder(Block):
        """Bare parameter/child holder so collect_params yields the
        canonical ``expert.*`` structural names the ep sharding rule
        (``spmd.param_spec``) and ShardingPlans match on."""

    class MoEBlock(Block):
        """Dense-dispatch top-k MoE FFN (:func:`moe_layer`) as a gluon
        block in the one donated step program.

        Parameters are named for the ep placement contract —
        ``gate.weight [M, E]`` (replicated), ``expert.ffn_1.weight
        [E, M, H]`` and ``expert.ffn_2.weight [E, H, M]`` (sharded
        ``P('ep')`` on dim 0 by name-aware ``spmd.param_spec`` when the
        mesh has a real ``ep`` axis).  The gating load-balance aux loss
        is recorded into the ambient :func:`aux_scope`; the TrainStep
        folds ``MXNET_MOE_AUX_WEIGHT * sum`` into the differentiated
        loss heads on both the compiled and eager paths, so the balance
        penalty reaches the optimizer without widening the user's
        loss_fn contract.  Input ``x`` is ``[G, S, M]`` (groups, tokens,
        model dim); output matches.
        """

        def __init__(self, units: int, hidden: int, num_experts: int, *,
                     k: int = 2, capacity_factor: float = 1.25,
                     capacity: Optional[int] = None,
                     activation=jax.nn.gelu, dtype: str = "float32"):
            super().__init__()
            self._units = units
            self._hidden = hidden
            self._num_experts = num_experts
            self._k = k
            self._capacity_factor = capacity_factor
            self._capacity = capacity
            self._activation = activation
            self.gate = _Holder()
            self.gate.weight = Parameter(
                "weight", shape=(units, num_experts), dtype=dtype)
            self.expert = _Holder()
            self.expert.ffn_1 = _Holder()
            self.expert.ffn_1.weight = Parameter(
                "weight", shape=(num_experts, units, hidden), dtype=dtype)
            self.expert.ffn_2 = _Holder()
            self.expert.ffn_2.weight = Parameter(
                "weight", shape=(num_experts, hidden, units), dtype=dtype)

        def _moe_fn(self):
            kw = dict(k=self._k, capacity_factor=self._capacity_factor,
                      capacity=self._capacity,
                      activation=self._activation)

            def fn(x, gw, wi, wo):
                return moe_layer(x, gw, wi, wo, **kw)

            return fn

        def forward(self, x):
            gw = self.gate.weight.data()
            wi = self.expert.ffn_1.weight.data()
            wo = self.expert.ffn_2.weight.data()
            if _ag.is_recording() and not isinstance(
                    gw._data, jax.core.Tracer):
                out, aux = jax_bridge(self._moe_fn(), x, gw, wi, wo)
                record_aux(aux)
                return out
            ctx = x.ctx if isinstance(x, NDArray) else current_context()
            raw = x._data if isinstance(x, NDArray) else jnp.asarray(x)
            out, aux = self._moe_fn()(raw, gw._data, wi._data, wo._data)
            record_aux(aux)
            return _wrap(out, ctx)

    _MOE_BLOCK_CLS = MoEBlock
    return _MOE_BLOCK_CLS


def __getattr__(name):
    if name == "MoEBlock":
        return _moe_block_cls()
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
