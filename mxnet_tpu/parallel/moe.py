"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

No MoE exists in the reference (SURVEY.md §5); this is forward-looking
capability required for the TPU build's first-class distributed story.
Design follows the standard TPU recipe: top-k gating with capacity,
einsum-based dense dispatch/combine (MXU-friendly, no dynamic shapes), expert
weights sharded over ``ep`` so the dispatch einsum lowers to an all-to-all
over ICI.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["top_k_gating", "moe_layer"]


def top_k_gating(x, gate_w, *, num_experts: int, k: int = 2,
                 capacity_factor: float = 1.25,
                 capacity: Optional[int] = None):
    """Compute dispatch/combine tensors for top-k routing.

    x: [G, S, M] (groups=batch shards, tokens, model dim)
    gate_w: [M, E]
    Returns (dispatch [G, S, E, C] bool-ish float, combine [G, S, E, C],
    aux_loss scalar).  Static shapes throughout: tokens over capacity C are
    dropped (their combine weights are zero), the standard TPU trick to keep
    XLA shapes static (vs the reference's dynamic-shape boolean_mask ops).
    """
    G, S, M = x.shape
    E = num_experts
    if capacity is None:
        capacity = max(1, int(capacity_factor * S * k / E))
    C = capacity

    logits = jnp.einsum("gsm,me->gse", x, gate_w)
    probs = jax.nn.softmax(logits, axis=-1)

    # load-balancing auxiliary loss (Shazeer et al.): mean prob * mean assignment
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=1)                               # [G, E]
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=x.dtype), axis=1)
    aux_loss = jnp.mean(jnp.sum(me * ce, axis=-1)) * E

    dispatch = jnp.zeros((G, S, E, C), dtype=x.dtype)
    combine = jnp.zeros((G, S, E, C), dtype=x.dtype)
    # running per-expert position counters, updated as we take each of k choices
    position_in_expert = jnp.zeros((G, E), dtype=jnp.int32)
    p = probs
    for _ in range(k):
        idx = jnp.argmax(p, axis=-1)                            # [G, S]
        gate = jnp.take_along_axis(p, idx[..., None], axis=-1)[..., 0]
        p = p * (1.0 - jax.nn.one_hot(idx, E, dtype=p.dtype))   # mask chosen
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)        # [G, S, E]
        # position of each token within its chosen expert's queue
        pos = position_in_expert[:, None, :] + jnp.cumsum(onehot, axis=1) - onehot
        pos_tok = jnp.sum(pos * onehot, axis=-1)                # [G, S]
        position_in_expert = position_in_expert + jnp.sum(onehot, axis=1)
        keep = (pos_tok < C).astype(x.dtype)                    # capacity drop
        gate = gate * keep
        pos_oh = jax.nn.one_hot(jnp.minimum(pos_tok, C - 1), C, dtype=x.dtype)
        contrib = onehot.astype(x.dtype)[..., None] * pos_oh[:, :, None, :]
        dispatch = dispatch + contrib * keep[..., None, None]
        combine = combine + contrib * gate[..., None, None]
    return dispatch, combine, aux_loss


def moe_layer(x, gate_w, w_in, w_out, *, k: int = 2,
              capacity_factor: float = 1.25, capacity: Optional[int] = None,
              activation=jax.nn.gelu) -> Tuple[jax.Array, jax.Array]:
    """Dense-dispatch MoE FFN.

    x: [G, S, M]; gate_w: [M, E]; w_in: [E, M, H]; w_out: [E, H, M].
    Shard w_in/w_out over 'ep' on dim 0 (ShardingPlan rule `expert.*`) and
    XLA turns the dispatch einsums into all-to-alls over the ep axis.
    Returns (output [G, S, M], aux_loss).
    """
    E = gate_w.shape[-1]
    dispatch, combine, aux = top_k_gating(
        x, gate_w, num_experts=E, k=k, capacity_factor=capacity_factor,
        capacity=capacity)
    # [G,S,E,C] x [G,S,M] -> expert inputs [E, G, C, M]
    expert_in = jnp.einsum("gsec,gsm->egcm", dispatch, x)
    h = activation(jnp.einsum("egcm,emh->egch", expert_in, w_in))
    expert_out = jnp.einsum("egch,ehm->egcm", h, w_out)
    out = jnp.einsum("gsec,egcm->gsm", combine, expert_out)
    return out, aux
