"""Pipeline parallelism over the ``pp`` mesh axis.

Absent from the reference (only manual device placement existed; SURVEY.md
§2.3).  TPU-native design: all pipeline stages have identical structure
(stage params stacked on a leading axis sharded over ``pp``), and the
schedule is a GPipe loop written as ``lax.scan`` inside ``shard_map`` —
activations move between neighbour devices with ``ppermute`` (one ICI hop),
microbatches fill/drain the bubble.

This is the "collective pipelining" pattern: because every device runs the
same scanned program on its own stage's weights, the whole pipeline is one
SPMD computation XLA can overlap (permute of microbatch i+1 rides under
compute of microbatch i).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_apply", "pipelined", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[{name: arr}, ...] (one dict per stage, same structure) ->
    {name: arr stacked on new leading stage axis} — shard dim 0 over 'pp'."""
    keys = per_stage_params[0].keys()
    return {k: jnp.stack([p[k] for p in per_stage_params]) for k in keys}


def pipeline_apply(stage_fn: Callable, stacked_params, x, *,
                   num_microbatches: int, axis_name: str = "pp"):
    """Run ``stage_fn(params, act) -> act`` through all pipeline stages.

    Call INSIDE shard_map: ``stacked_params`` leaves have a leading stage dim
    already sharded to size 1 locally (this device's stage); ``x`` is the
    full batch input [B, ...] present on stage 0 (replicated arrival is fine
    — non-first stages ignore their input).  Returns the final stage's
    output, valid on the LAST stage (others hold garbage; caller selects).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    local_params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

    B = x.shape[0]
    assert B % num_microbatches == 0, "batch must divide microbatches"
    mb = B // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    total_steps = num_microbatches + n - 1
    buf = jnp.zeros((mb,) + x.shape[1:], dtype=x.dtype)      # inbound act
    outs = jnp.zeros((num_microbatches, mb) + x.shape[1:], dtype=x.dtype)

    def step(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (while t < num_microbatches)
        feed = micro[jnp.minimum(t, num_microbatches - 1)]
        cur = jnp.where(idx == 0, feed, buf)
        act = stage_fn(local_params, cur)
        # last stage records its result for microbatch t - (n-1)
        out_slot = t - (n - 1)
        outs = jnp.where(
            (idx == n - 1) & (out_slot >= 0),
            lax.dynamic_update_index_in_dim(
                outs, act, jnp.clip(out_slot, 0, num_microbatches - 1), 0),
            outs)
        # shift activations forward one stage
        perm = [(i, (i + 1) % n) for i in range(n)]
        buf = lax.ppermute(act, axis_name, perm=perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(step, (buf, outs), jnp.arange(total_steps))
    out = outs.reshape((B,) + x.shape[1:])
    # deliver final output from last stage to all (so loss is replicated)
    src = n - 1
    mask = (idx == src).astype(out.dtype)
    return lax.psum(out * mask, axis_name)


def pipelined(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
              axis_name: str = "pp", param_spec=None, x_spec=None):
    """shard_map wrapper: stacked params sharded over pp on dim 0, input
    replicated over pp, output replicated."""
    if param_spec is None:
        param_spec = P(axis_name)
    if x_spec is None:
        x_spec = P()
    fn = partial(pipeline_apply, stage_fn, num_microbatches=num_microbatches,
                 axis_name=axis_name)
    return shard_map(fn, mesh=mesh, in_specs=(param_spec, x_spec),
                     out_specs=P(), check_vma=False)
