"""Pipeline parallelism over the ``pp`` mesh axis.

Absent from the reference (only manual device placement existed; SURVEY.md
§2.3).  TPU-native design: the schedule is a GPipe loop written as
``lax.scan`` inside ``shard_map`` — activations move between neighbour
devices with ``ppermute`` (one ICI hop), microbatches fill/drain the bubble.

This is the "collective pipelining" pattern: because every device runs the
same scanned program on its own stage's weights, the whole pipeline is one
SPMD computation XLA can overlap (permute of microbatch i+1 rides under
compute of microbatch i).

Two APIs:

- :func:`pipelined` — fast path for *identical* stages (stage params stacked
  on a leading axis sharded over ``pp``, shape-preserving stage fn).
- :class:`HeteroPipeline` — *heterogeneous* stages (e.g. embed → block stack
  → head) with per-stage functions, per-stage parameter pytrees, and
  non-shape-preserving boundaries.  Each stage's params are flattened into
  one padded fp32 buffer; the buffers are stacked into ``[n_stages, P]``
  sharded over ``pp`` so device *i* holds only stage *i*'s weights.  Stage
  dispatch is a ``lax.switch`` on the device's pp index; activations cross
  stage boundaries in a packed "wire" buffer sized to the largest boundary
  (specs derived once via ``jax.eval_shape``).  Microbatch gradient
  accumulation is inherent: differentiating through the scan sums each
  stage's weight gradient over all its microbatches (GPipe schedule); with
  ``remat=True`` each per-step stage call is rematerialised in the backward
  pass, bounding live activation memory to the 1F1B profile (wire buffers
  only) instead of full GPipe stashes.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map as _jax_shard_map
except ImportError:      # this jax ships it under experimental
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(*args, **kwargs):
    """shard_map with the check_vma kwarg mapped onto older jax's
    check_rep spelling (renamed upstream; semantics unchanged here)."""
    try:
        return _jax_shard_map(*args, **kwargs)
    except TypeError:
        if "check_vma" in kwargs:
            kwargs = dict(kwargs)
            kwargs["check_rep"] = kwargs.pop("check_vma")
            return _jax_shard_map(*args, **kwargs)
        raise

__all__ = ["pipeline_apply", "pipelined", "stack_stage_params",
           "HeteroPipeline", "PipelineBlock", "bubble_fraction"]

# largest integer magnitude fp32 represents exactly: the packed wire
# casts every leaf to fp32, so wider values would silently round
_WIRE_EXACT_MAX = 2 ** 24


def bubble_fraction(n_stages: int, num_microbatches: int) -> float:
    """GPipe bubble fraction: the fill/drain steps (``n_stages - 1``) as a
    share of the whole schedule (``num_microbatches + n_stages - 1``)."""
    return (n_stages - 1) / float(num_microbatches + n_stages - 1)


def _wire_wide_int(dtype) -> bool:
    dt = jnp.dtype(dtype)
    return dt.kind in "iu" and dt.itemsize >= 4


def _check_wire_tree(tree, where: str, *, allow_abstract_32: bool = False):
    """Refuse leaves the packed fp32 wire cannot carry exactly.

    Narrow integers (bool/int8/int16/uint8/uint16) always round-trip.
    Wide integers (>= 32-bit) round-trip only below 2**24: concrete
    leaves are value-checked; abstract leaves (``jax.eval_shape``-derived
    stage boundaries, ShapeDtypeStruct examples) cannot be bounds-checked
    at wire-spec derivation time, so they refuse — except 32-bit example
    INPUTS when ``allow_abstract_32`` (the documented token-id path,
    vocab ids << 2**24).  Raising here, at ``HeteroPipeline.__init__``,
    replaces the old silent precision loss in ``_tree_pack`` /
    ``_batched_pack``.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        if not _wire_wide_int(getattr(leaf, "dtype", jnp.float32)):
            continue
        name = jax.tree_util.keystr(path) or "<root>"
        dt = jnp.dtype(leaf.dtype)
        from ..base import MXNetError

        concrete = not isinstance(leaf, jax.ShapeDtypeStruct) and \
            hasattr(leaf, "__array__")
        if concrete:
            # graftlint: disable=host-sync -- one-time __init__ validation
            # of concrete example/param values, never inside the step
            arr = onp.asarray(leaf)
            vmax = max(abs(int(arr.min())), abs(int(arr.max()))) \
                if arr.size else 0
            if vmax >= _WIRE_EXACT_MAX:
                raise MXNetError(
                    f"HeteroPipeline wire precision: {where} leaf "
                    f"{name} (dtype {dt.name}) holds |value| {vmax} >= "
                    "2**24, which the packed fp32 wire cannot represent "
                    "exactly. Keep integer leaves below 2**24 or cast "
                    "to float32 (or a <=16-bit integer) before the "
                    "pipeline boundary.")
            continue
        if dt.itemsize == 4 and allow_abstract_32:
            continue
        raise MXNetError(
            f"HeteroPipeline wire precision: {where} leaf {name} has "
            f"abstract dtype {dt.name}; integer values >= 2**24 do not "
            "round-trip through the packed fp32 wire and a "
            f"{'64-bit' if dt.itemsize >= 8 else 'computed'} integer "
            "boundary cannot be bounds-checked at wire-spec derivation "
            "time. Cast to float32 (or a <=16-bit integer) at the "
            "stage boundary.")


def stack_stage_params(per_stage_params):
    """[{name: arr}, ...] (one dict per stage, same structure) ->
    {name: arr stacked on new leading stage axis} — shard dim 0 over 'pp'."""
    keys = per_stage_params[0].keys()
    return {k: jnp.stack([p[k] for p in per_stage_params]) for k in keys}


def pipeline_apply(stage_fn: Callable, stacked_params, x, *,
                   num_microbatches: int, axis_name: str = "pp"):
    """Run ``stage_fn(params, act) -> act`` through all pipeline stages.

    Call INSIDE shard_map: ``stacked_params`` leaves have a leading stage dim
    already sharded to size 1 locally (this device's stage); ``x`` is the
    full batch input [B, ...] present on stage 0 (replicated arrival is fine
    — non-first stages ignore their input).  Returns the final stage's
    output, valid on the LAST stage (others hold garbage; caller selects).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    local_params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

    B = x.shape[0]
    assert B % num_microbatches == 0, "batch must divide microbatches"
    mb = B // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    total_steps = num_microbatches + n - 1
    buf = jnp.zeros((mb,) + x.shape[1:], dtype=x.dtype)      # inbound act
    outs = jnp.zeros((num_microbatches, mb) + x.shape[1:], dtype=x.dtype)

    def step(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (while t < num_microbatches)
        feed = micro[jnp.minimum(t, num_microbatches - 1)]
        cur = jnp.where(idx == 0, feed, buf)
        act = stage_fn(local_params, cur)
        # last stage records its result for microbatch t - (n-1)
        out_slot = t - (n - 1)
        outs = jnp.where(
            (idx == n - 1) & (out_slot >= 0),
            lax.dynamic_update_index_in_dim(
                outs, act, jnp.clip(out_slot, 0, num_microbatches - 1), 0),
            outs)
        # shift activations forward one stage
        perm = [(i, (i + 1) % n) for i in range(n)]
        buf = lax.ppermute(act, axis_name, perm=perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(step, (buf, outs), jnp.arange(total_steps))
    out = outs.reshape((B,) + x.shape[1:])
    # deliver final output from last stage to all (so loss is replicated)
    src = n - 1
    mask = (idx == src).astype(out.dtype)
    return lax.psum(out * mask, axis_name)


def pipelined(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
              axis_name: str = "pp", param_spec=None, x_spec=None):
    """shard_map wrapper: stacked params sharded over pp on dim 0, input
    replicated over pp, output replicated."""
    if param_spec is None:
        param_spec = P(axis_name)
    if x_spec is None:
        x_spec = P()
    fn = partial(pipeline_apply, stage_fn, num_microbatches=num_microbatches,
                 axis_name=axis_name)
    return shard_map(fn, mesh=mesh, in_specs=(param_spec, x_spec),
                     out_specs=P(), check_vma=False)


# ---------------------------------------------------------------------------
# Heterogeneous pipeline
# ---------------------------------------------------------------------------

def _tree_pack_spec(tree):
    """(treedef, [(shape, dtype, offset, size)], total_size) for packing a
    pytree into one flat fp32 vector."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs, off = [], 0
    for leaf in leaves:
        n = int(onp.prod(leaf.shape)) if leaf.shape else 1
        specs.append((tuple(leaf.shape), jnp.dtype(leaf.dtype), off, n))
        off += n
    return treedef, specs, off


def _tree_pack(tree, size: int):
    """Flatten + concat a pytree into an fp32 vector padded to ``size``.

    Integer leaves are value-cast (exact below 2**24 — tokens/labels); all
    float leaves round-trip exactly through fp32 except fp64 (unused here).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((size,), jnp.float32)
    flat = jnp.concatenate(
        [jnp.ravel(l).astype(jnp.float32) for l in leaves])
    return jnp.pad(flat, (0, size - flat.shape[0]))


def _tree_unpack(buf, treedef, specs):
    leaves = [
        lax.slice(buf, (off,), (off + n,)).reshape(shape).astype(dtype)
        for (shape, dtype, off, n) in specs
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _batched_pack_spec(tree):
    """Like _tree_pack_spec but leaves keep a leading batch dim; specs are
    per-sample (shape[1:])."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs, off = [], 0
    for leaf in leaves:
        per = int(onp.prod(leaf.shape[1:])) if len(leaf.shape) > 1 else 1
        specs.append((tuple(leaf.shape[1:]), jnp.dtype(leaf.dtype), off, per))
        off += per
    return treedef, specs, off


def _batched_pack(tree, size: int):
    """Pack [B, ...] leaves into [B, size] fp32 wire buffer."""
    leaves = jax.tree_util.tree_leaves(tree)
    B = leaves[0].shape[0]
    flat = jnp.concatenate(
        [l.reshape(B, -1).astype(jnp.float32) for l in leaves], axis=1)
    return jnp.pad(flat, ((0, 0), (0, size - flat.shape[1])))


def _batched_unpack(buf, treedef, specs):
    B = buf.shape[0]
    leaves = [
        lax.slice(buf, (0, off), (B, off + n)).reshape((B,) + shape)
        .astype(dtype)
        for (shape, dtype, off, n) in specs
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class HeteroPipeline:
    """GPipe pipeline with heterogeneous stages over the ``pp`` mesh axis.

    The reference has no pipeline parallelism at all (SURVEY.md §2.3); this
    is TPU-native surplus.  Design notes in the module docstring.

    Parameters
    ----------
    stage_fns : list of ``fn(stage_params, act, *extras) -> act``
        One per pipeline stage.  ``act`` is a pytree of arrays with leading
        (micro)batch dim; output boundary shapes may differ per stage.
        ``extras`` are per-microbatch side inputs (e.g. labels) delivered to
        every stage indexed by *that stage's* current microbatch.
    stage_params : list of pytrees (one per stage, structures may differ).
    mesh : Mesh with a ``pp`` axis of size ``len(stage_fns)`` (a ``dp``
        axis, if present, shards every batch dim).
    num_microbatches : microbatch count (must divide the global batch).
    example_x / example_extras : concrete or ShapeDtypeStruct trees used
        once with ``jax.eval_shape`` to derive the wire format.
    remat : rematerialise each stage call in backward (1F1B-like memory).
    """

    def __init__(self, stage_fns: Sequence[Callable],
                 stage_params: Sequence[Any], mesh: Mesh, *,
                 num_microbatches: int, example_x: Any,
                 example_extras: Tuple[Any, ...] = (),
                 axis_name: str = "pp", batch_axis: str = "dp",
                 remat: bool = False):
        n = len(stage_fns)
        assert n == len(stage_params), "one param tree per stage"
        assert mesh.shape.get(axis_name, 1) == n, (
            f"mesh axis '{axis_name}' (size {mesh.shape.get(axis_name, 1)}) "
            f"must equal number of stages ({n})")
        self.stage_fns = list(stage_fns)
        self.mesh = mesh
        self.axis_name = axis_name
        self.batch_axis = batch_axis if batch_axis in mesh.shape else None
        self.num_microbatches = num_microbatches
        self.n_stages = n
        self.remat = remat

        # ---- wire-exactness validation (satellite of the fp32 wire) -----
        # every stage's params and every activation boundary cross the
        # packed fp32 wire; refuse leaves it cannot carry exactly HERE,
        # at wire-spec derivation time, instead of silently rounding
        for j, p in enumerate(stage_params):
            _check_wire_tree(p, f"stage {j} param")
        _check_wire_tree(example_x, "pipeline input (example_x)",
                         allow_abstract_32=True)

        # ---- per-stage param pack specs (static) ------------------------
        self._p_specs = [_tree_pack_spec(p) for p in stage_params]
        self._p_size = max(s[2] for s in self._p_specs) or 1
        # leaf paths (keystr) per stage, aligned with pack-spec order, so
        # callers can locate a named leaf inside the packed buffer (used for
        # cross-stage weight tying)
        self._p_paths = [
            [jax.tree_util.keystr(path) for path, _ in
             jax.tree_util.tree_flatten_with_path(p)[0]]
            for p in stage_params
        ]
        self.packed_params = self._pack_stage_params(stage_params)

        # ---- wire format: trace boundary shapes once --------------------
        dp = mesh.shape.get(batch_axis, 1) if self.batch_axis else 1
        leaves = jax.tree_util.tree_leaves(example_x)
        B = leaves[0].shape[0]
        assert B % (num_microbatches * dp) == 0, (
            f"batch {B} must divide num_microbatches*dp "
            f"({num_microbatches}x{dp})")
        mb = B // (num_microbatches * dp)  # per-device microbatch

        def _mb_struct(tree):
            return jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct((mb,) + tuple(l.shape[1:]),
                                               l.dtype), tree)

        self._example_extras = tuple(example_extras)
        extras_mb = tuple(_mb_struct(e) for e in example_extras)
        boundary = _mb_struct(example_x)
        self._b_specs = []           # input boundary spec per stage
        for j, fn in enumerate(self.stage_fns):
            self._b_specs.append(_batched_pack_spec(boundary))
            boundary = jax.eval_shape(fn, stage_params[j], boundary,
                                      *extras_mb)
            # computed inter-stage boundaries are abstract by
            # construction — wide-int outputs refuse loudly here
            _check_wire_tree(boundary, f"stage {j} output boundary")
        self._out_spec = _batched_pack_spec(boundary)   # last stage output
        self._w_size = max([s[2] for s in self._b_specs]
                           + [self._out_spec[2]])
        self._mb = mb
        self._apply = self._build_apply()

    # -- params -----------------------------------------------------------
    def _pack_stage_params(self, stage_params):
        bufs = [_tree_pack(p, self._p_size) for p in stage_params]
        stacked = jnp.stack(bufs)
        return jax.device_put(
            stacked, NamedSharding(self.mesh, P(self.axis_name, None)))

    def unpack_stage_params(self, packed=None) -> List[Any]:
        """[n_stages, P] buffer -> list of per-stage param pytrees."""
        if packed is None:
            packed = self.packed_params
        out = []
        for j, (treedef, specs, _) in enumerate(self._p_specs):
            out.append(_tree_unpack(packed[j], treedef, specs))
        return out

    def leaf_slice(self, stage: int, key: str) -> Tuple[int, int]:
        """(offset, size) of the named leaf inside stage ``stage``'s packed
        row.  ``key`` is the leaf's final pytree key (e.g. the dict key
        ``'embed.weight'``), matched exactly as the last path component."""
        want = f"['{key}']"
        for path, (shape, dtype, off, n) in zip(self._p_paths[stage],
                                                self._p_specs[stage][1]):
            if path == want or path.endswith(want):
                return off, n
        raise KeyError(f"no leaf matching {key!r} in stage {stage}: "
                       f"{self._p_paths[stage]}")

    def tie_grads(self, grads, ties):
        """Sum gradient slices of weight-tied leaves living on different
        stages and write the sum back to every member (Megatron-style tied
        embed/head).  ``grads`` is a [n_stages, P] packed cotangent;
        ``ties`` is an iterable of ((stage, key), (stage, key), ...)
        groups.  If the tied weights start equal and share one optimizer
        update rule, identical summed grads keep them exactly tied."""
        for group in ties:
            slices = [self.leaf_slice(s, k) for s, k in group]
            n = slices[0][1]
            assert all(sz == n for _, sz in slices), "tied leaves differ"
            total = sum(
                lax.dynamic_slice(grads, (s, off), (1, n))
                for (s, k), (off, _) in zip(group, slices))
            for (s, k), (off, _) in zip(group, slices):
                grads = lax.dynamic_update_slice(grads, total, (s, off))
        return grads

    # -- forward ----------------------------------------------------------
    def _build_apply(self):
        n = self.n_stages
        num_micro = self.num_microbatches
        W, mb = self._w_size, self._mb
        axis = self.axis_name
        b_specs, out_spec, p_specs = self._b_specs, self._out_spec, \
            self._p_specs
        stage_fns, remat = self.stage_fns, self.remat

        def device_fn(packed_params, x_wire, *extras):
            # packed_params [1, P] (this device's stage), x_wire
            # [num_micro, mb, W] (replicated over pp, sharded over dp)
            idx = lax.axis_index(axis)
            pbuf = packed_params[0]

            def run_stage(j, wire_in, extras_mb):
                params = _tree_unpack(pbuf, p_specs[j][0], p_specs[j][1])
                act = _batched_unpack(wire_in, b_specs[j][0], b_specs[j][1])
                out = stage_fns[j](params, act, *extras_mb)
                return _batched_pack(out, W)

            branches = [partial(run_stage, j) for j in range(n)]
            if remat:
                branches = [jax.checkpoint(b) for b in branches]

            def step(carry, t):
                buf, outs = carry
                feed = x_wire[jnp.clip(t, 0, num_micro - 1)]
                cur = jnp.where(idx == 0, feed, buf)
                # this device's current microbatch (clipped during
                # fill/drain; garbage steps are never recorded)
                mb_idx = jnp.clip(t - idx, 0, num_micro - 1)
                extras_mb = jax.tree_util.tree_map(
                    lambda e: e[mb_idx], extras)
                act = lax.switch(jnp.minimum(idx, n - 1), branches, cur,
                                 extras_mb)
                out_slot = t - (n - 1)
                outs = jnp.where(
                    (idx == n - 1) & (out_slot >= 0),
                    lax.dynamic_update_index_in_dim(
                        outs, act, jnp.clip(out_slot, 0, num_micro - 1), 0),
                    outs)
                perm = [(i, (i + 1) % n) for i in range(n)]
                buf = lax.ppermute(act, axis, perm=perm)
                return (buf, outs), None

            buf0 = jnp.zeros((mb, W), jnp.float32)
            outs0 = jnp.zeros((num_micro, mb, W), jnp.float32)
            (_, outs), _ = lax.scan(step, (buf0, outs0),
                                    jnp.arange(num_micro + n - 1))
            # deliver outputs from the last stage to all pp ranks so the
            # loss/grad is replicated over pp
            mask = (idx == n - 1).astype(outs.dtype)
            return lax.psum(outs * mask, axis)

        dp = self.batch_axis
        wire_spec = P(None, dp, None)
        extra_spec = P(None, dp)
        # shard_map is built ONCE (specs depend only on the extras structure
        # known at __init__) so eager pipe.apply calls hit jax's trace cache
        fn = shard_map(
            device_fn, mesh=self.mesh,
            in_specs=(P(axis, None), wire_spec)
            + tuple(jax.tree_util.tree_map(lambda _: extra_spec, e)
                    for e in self._example_extras),
            out_specs=wire_spec, check_vma=False)

        def apply(packed_params, x, *extras):
            # reshape [B, ...] -> [num_micro, mb*dp, ...] wire-packed
            leaves = jax.tree_util.tree_leaves(x)
            B = leaves[0].shape[0]
            gmb = B // num_micro    # global microbatch (pre-dp-shard)

            def to_micro(tree):
                return jax.tree_util.tree_map(
                    lambda l: l.reshape((num_micro, gmb) + l.shape[1:]),
                    tree)

            xm = to_micro(x)
            x_wire = jax.vmap(lambda t: _batched_pack(t, W))(xm)
            extras_m = tuple(to_micro(e) for e in extras)
            out_wire = fn(packed_params, x_wire, *extras_m)
            out = jax.vmap(
                lambda t: _batched_unpack(t, out_spec[0], out_spec[1])
            )(out_wire)
            # merge microbatch dim back into batch
            return jax.tree_util.tree_map(
                lambda l: l.reshape((num_micro * l.shape[1],) + l.shape[2:]),
                out)

        return apply

    def apply(self, packed_params, x, *extras):
        """Run the full pipeline: ``x`` [B, ...] -> last-stage outputs
        [B, ...] (microbatching is internal).  Differentiable w.r.t.
        ``packed_params``."""
        return self._apply(packed_params, x, *extras)


# ---------------------------------------------------------------------------
# Gluon adapter: the pipeline as a trainable Block in the one donated step
# ---------------------------------------------------------------------------

_PIPELINE_BLOCK_CLS = None


def _pipeline_block_cls():
    """Build the PipelineBlock class lazily: gluon imports here (not at
    module import) keep ``mxnet_tpu.parallel`` free of an import cycle
    through the gluon package."""
    global _PIPELINE_BLOCK_CLS
    if _PIPELINE_BLOCK_CLS is not None:
        return _PIPELINE_BLOCK_CLS

    from .. import autograd as _ag
    from ..context import current_context
    from ..gluon.block import Block, jax_bridge
    from ..gluon.parameter import Parameter
    from ..ndarray import NDArray
    from ..ndarray.ndarray import _wrap

    class PipelineBlock(Block):
        """A :class:`HeteroPipeline` as a gluon block: ONE trainable
        parameter — the packed ``[n_stages, P]`` fp32 stage buffer —
        so ``Trainer.compile_step`` traces the pipeline's scan-internal
        microbatch schedule into the single donated step program (one
        dispatch per step; N+1 per window under gradient accumulation).

        The packed parameter is named ``pp_stages``: under a mesh with a
        real ``pp`` axis, ``spmd.param_spec`` places it ``P('pp', None)``
        (device *i* holds stage *i*'s weights) and the fused optimizer
        updates it elementwise in packed space — exact, since packing is
        a concat of fp32 leaves and padding sees zero grads.  Gradients
        of weight-tied leaves (``pipe.tied``) are summed across stages
        via :meth:`compiled_grad_transform`, which the TrainStep applies
        inside the compiled program right after the vjp.

        On the eager tape (compiled-step fallback) the forward routes
        through :func:`gluon.block.jax_bridge`, so autograd still
        differentiates the shard_map schedule; batch shape is fixed to
        the wire derived at ``HeteroPipeline.__init__``.
        """

        def __init__(self, pipe: HeteroPipeline):
            super().__init__()
            self._pipe = pipe
            packed = pipe.packed_params
            ctx = current_context()
            self.pp_stages = Parameter(
                "pp_stages", shape=tuple(packed.shape), dtype="float32")
            # the value IS the packed buffer — install it directly
            # (the name-pattern default initializer doesn't know it)
            self.pp_stages._load_init(_wrap(packed, ctx), ctx=[ctx])

        @property
        def pipe(self) -> HeteroPipeline:
            return self._pipe

        def unpack_stage_params(self):
            """Per-stage param pytrees from the CURRENT parameter value
            (``pipe.packed_params`` keeps only the initial buffer)."""
            return self._pipe.unpack_stage_params(
                self.pp_stages.data()._data)

        def compiled_grad_transform(self, named_grads):
            """TrainStep grad hook: sum tied-leaf gradient slices across
            stages (Megatron-style tied embed/head) on the packed
            cotangent.  Linear, so per-microbatch application under
            accumulation equals application on the window sum."""
            ties = getattr(self._pipe, "tied", ())
            if not ties:
                return named_grads
            out = dict(named_grads)
            for name, g in named_grads.items():
                if name == "pp_stages" or name.endswith(".pp_stages"):
                    out[name] = self._pipe.tie_grads(g, ties)
            return out

        def forward(self, x, *extras):
            w = self.pp_stages.data()
            if _ag.is_recording() and not isinstance(
                    w._data, jax.core.Tracer):
                return jax_bridge(self._pipe.apply, w, x,
                                  *[e for e in extras])
            ctx = x.ctx if isinstance(x, NDArray) else current_context()
            raw = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                   for a in (x,) + tuple(extras)]
            out = self._pipe.apply(w._data, *raw)
            return jax.tree_util.tree_map(lambda l: _wrap(l, ctx), out)

    _PIPELINE_BLOCK_CLS = PipelineBlock
    return _PIPELINE_BLOCK_CLS


def __getattr__(name):
    if name == "PipelineBlock":
        return _pipeline_block_cls()
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
