"""Ring attention: sequence/context parallelism for long sequences.

The reference has NO long-context machinery (SURVEY.md §5: "no ring
attention, context/sequence parallelism ... anywhere" — its closest artifact
is the fused self-attention matmuls in src/operator/contrib/transformer.cc).
This module is the TPU-native replacement that makes sequence length a mesh
axis: Q/K/V are sharded over ``sp``; each step every device computes
attention of its local Q block against the K/V block currently resident,
then rotates K/V one hop around the ring (``ppermute`` on neighbour ICI
links), overlapping the next block's compute with the transfer.  Softmax is
accumulated online (flash-attention style running max / running sum), so the
full S×S score matrix never materializes.

Numerically identical to full softmax(QK^T/sqrt(d))V — verified in
tests/test_parallel.py against the dense reference on an 8-device CPU mesh.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map as _jax_shard_map
except ImportError:      # this jax ships it under experimental
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(*args, **kwargs):
    """shard_map with the check_vma kwarg mapped onto older jax's
    check_rep spelling (renamed upstream; semantics unchanged here)."""
    try:
        return _jax_shard_map(*args, **kwargs)
    except TypeError:
        if "check_vma" in kwargs:
            kwargs = dict(kwargs)
            kwargs["check_rep"] = kwargs.pop("check_vma")
            return _jax_shard_map(*args, **kwargs)
        raise

__all__ = ["ring_attention", "ring_attention_sharded", "local_attention_block"]


def local_attention_block(q, k, v, m_prev, l_prev, o_prev, *, scale,
                          mask=None):
    """One online-softmax accumulation step.

    q: [B, H, Sq, D]; k/v: [B, H, Sk, D];
    m_prev/l_prev: [B, H, Sq] running max / normalizer; o_prev: un-normalized
    output accumulator [B, H, Sq, D].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard: fully-masked rows keep m_new finite enough for exp
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    o_new = o_prev * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "sp", *, causal: bool = False,
                   scale: Optional[float] = None):
    """Blockwise ring attention over mesh axis ``axis_name``.

    Call INSIDE shard_map/pjit with q,k,v local shards [B, H, S_local, D].
    Sequence is laid out contiguously across the ring: device i holds tokens
    [i*S_local, (i+1)*S_local).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    Sk = k.shape[2]

    m0 = jnp.full((B, H, S), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, S), dtype=jnp.float32)
    o0 = jnp.zeros((B, H, S, D), dtype=jnp.float32)
    qf = q.astype(jnp.float32)

    def step(carry, t):
        m, l, o, kt, vt = carry
        # block kt/vt originated on device (my_idx + t) % n
        src = (my_idx + t) % n
        if causal:
            q_pos = my_idx * S + jnp.arange(S)
            k_pos = src * Sk + jnp.arange(Sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            mask = jnp.broadcast_to(mask[None, None], (B, H, S, Sk))
        else:
            mask = None
        m, l, o = local_attention_block(
            qf, kt.astype(jnp.float32), vt.astype(jnp.float32), m, l, o,
            scale=scale, mask=mask)
        # rotate k/v to the next device; overlap with next iteration's compute
        perm = [(i, (i - 1) % n) for i in range(n)]
        kt = lax.ppermute(kt, axis_name, perm=perm)
        vt = lax.ppermute(vt, axis_name, perm=perm)
        return (m, l, o, kt, vt), None

    (m, l, o, _, _), _ = lax.scan(step, (m0, l0, o0, k, v), jnp.arange(n))
    # fully-masked rows (causal, leading tokens on later devices) have l=0
    l = jnp.where(l == 0, 1.0, l)
    out = o / l[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                           causal: bool = False,
                           batch_axes=("dp",)):
    """Top-level entry: q,k,v are global arrays [B, H, S, D]; shards them
    over (batch_axes, sp) and runs the ring under shard_map."""
    spec = P(tuple(a for a in batch_axes if a in mesh.shape) or None, None,
             axis_name if axis_name in mesh.shape else None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
