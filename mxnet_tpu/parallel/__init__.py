"""mxnet_tpu.parallel — SPMD parallelism over TPU device meshes.

The reference's distributed layer (SURVEY.md §2.3: KVStore + Comm/NCCL/
ps-lite, data parallelism only) is replaced by declarative sharding of one
jitted program over a named ``jax.sharding.Mesh``:

- :mod:`mesh`       — mesh construction / current-mesh scope
- :mod:`sharding`   — ShardingPlan (name-pattern → PartitionSpec), fsdp/tp plans
- :mod:`spmd`       — kvstore='tpu' data-parallel mesh plumbing (the
  compiled-step / prefetcher / serving placement contract)
- :mod:`collectives`— KVStore-flavoured named collectives (psum/all_gather/…)
- :mod:`train`      — ShardedTrainer: whole train step as one SPMD program
- :mod:`ring_attention` — sequence/context parallelism (absent upstream)
- :mod:`moe`        — expert parallelism (absent upstream)
- :mod:`pipeline`   — GPipe-style pipeline stages over ``pp``
"""
from . import (collectives, elastic, mesh, moe, pipeline, ring_attention,
               sharding, spmd, train)
from .collectives import (all_gather, all_reduce, all_to_all, broadcast_from,
                          ppermute, reduce_scatter, ring_shift, run_sharded)
from .mesh import AXIS_NAMES, auto_mesh, current_mesh, make_mesh, mesh_scope, set_mesh
from .moe import moe_layer, top_k_gating
from .pipeline import (HeteroPipeline, pipeline_apply, pipelined,
                       stack_stage_params)
from .ring_attention import ring_attention, ring_attention_sharded
from .sharding import (PartitionSpec, ShardingPlan, constraint,
                       expert_parallel_plan, fsdp_plan, replicated_plan,
                       shard_array, tensor_parallel_plan)
from .train import ShardedTrainer, functional_call
from .elastic import CheckpointManager, HeartbeatMonitor, run_elastic

__all__ = [
    "AXIS_NAMES", "auto_mesh", "current_mesh", "make_mesh", "mesh_scope",
    "set_mesh", "ShardingPlan", "PartitionSpec", "constraint", "fsdp_plan",
    "expert_parallel_plan",
    "replicated_plan", "shard_array", "tensor_parallel_plan", "all_reduce",
    "all_gather", "reduce_scatter", "all_to_all", "ppermute", "ring_shift",
    "broadcast_from", "run_sharded", "ring_attention",
    "ring_attention_sharded", "moe_layer", "top_k_gating", "pipeline_apply",
    "pipelined", "stack_stage_params", "HeteroPipeline", "ShardedTrainer",
    "functional_call", "CheckpointManager", "HeartbeatMonitor",
    "run_elastic",
]
