"""KVStore server bootstrap (reference
``python/mxnet/kvstore/kvstore_server.py``).

The reference spawns dedicated parameter-server/scheduler processes
(ps-lite): ``DMLC_ROLE=server`` processes enter ``KVStoreServer.run()``.
TPU-native distributed training has NO parameter servers — gradients ride
ICI/DCN all-reduce collectives inside the compiled step — so the roles
collapse: every process is a worker (multi-controller JAX).  This module
keeps the bootstrap contract: role-driven entry that (a) initializes the
jax.distributed runtime from the launcher-provided env and (b) for
'server'/'scheduler' roles parks the process (ps-lite parity for scripts
that spawn them), so ``tools/launch.py`` jobs written against the
reference's flow run unchanged.
"""
from __future__ import annotations

import os
import time

from .. import config as _config

__all__ = ["KVStoreServer", "init_distributed", "role"]


def role() -> str:
    return (_config.get("DMLC_ROLE") or _config.get("MXNET_ROLE")
            or "worker")


def init_distributed() -> bool:
    """Initialize jax.distributed from launcher env (idempotent).

    Env contract (set by tools/launch.py):
      MXNET_TPU_COORDINATOR  host:port of process 0
      MXNET_TPU_NUM_PROCS    world size
      MXNET_TPU_PROC_ID      this process' rank
    """
    coord = _config.get("MXNET_TPU_COORDINATOR")
    if not coord:
        return False
    import jax

    if getattr(init_distributed, "_done", False):
        return True
    num_procs = _config.get("MXNET_TPU_NUM_PROCS")
    proc_id = _config.get("MXNET_TPU_PROC_ID")
    if num_procs is None or proc_id is None:
        raise KeyError(
            "MXNET_TPU_COORDINATOR is set but MXNET_TPU_NUM_PROCS/"
            "MXNET_TPU_PROC_ID are not — tools/launch.py sets all three")
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(num_procs),
        process_id=int(proc_id))
    init_distributed._done = True
    return True


class KVStoreServer:
    """Role shim (reference KVStoreServer.run listening loop).

    Collectives replace server-side aggregation on TPU; a 'server' role
    process simply parks until the job ends so launch scripts that spawn
    scheduler/server roles keep working."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):
        r = role()
        if r == "worker":
            raise RuntimeError("KVStoreServer.run() called in a worker "
                               "process")
        # park: reference servers block in the ps-lite event loop
        stop_file = _config.get("MXNET_TPU_STOP_FILE")
        while True:
            if stop_file and os.path.exists(stop_file):
                return
            time.sleep(0.2)
