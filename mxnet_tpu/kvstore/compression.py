"""2-bit gradient compression with error feedback.

Reference: ``src/kvstore/gradient_compression.{h,cc,cu}`` (kTwoBit,
gradient_compression.h:38; Quantize/Dequantize :111-121) — worker-side
quantization applied before the dist push, with the quantization error
kept as a residual added to the next gradient.

Quantization rule (matches the reference's 2-bit kernel and the expected
values computed by tests/nightly/dist_sync_kvstore.py):

    x >  threshold  ->  +threshold   (code 01)
    x < -threshold  ->  -threshold   (code 10)
    else            ->   0           (code 00)

On the wire, 16 two-bit codes pack into one uint32 — a 16x reduction of
cross-host (DCN) bytes versus raw fp32 gradients.  TPU-native layout:
pack/unpack are pure jnp bit ops, so they fuse into the surrounding
XLA program on either side of the collective.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

__all__ = ["GradientCompression"]


class GradientCompression:
    """Stateful per-key 2-bit compressor (error-feedback residuals live
    here, one per key, matching the reference's per-key residual_ array)."""

    def __init__(self, type: str = "2bit", threshold: float = 0.5):
        if type != "2bit":
            raise ValueError(f"unsupported compression type {type!r}")
        self.type = type
        self.threshold = float(threshold)
        if self.threshold <= 0:
            raise ValueError("threshold must be positive")
        self._residuals: Dict[str, jnp.ndarray] = {}

    # -- quantize / codes -------------------------------------------------
    def quantize(self, x: jnp.ndarray) -> jnp.ndarray:
        t = self.threshold
        return jnp.where(x > t, t, jnp.where(x < -t, -t, 0.0)).astype(
            jnp.float32)

    def codes(self, x: jnp.ndarray) -> jnp.ndarray:
        t = self.threshold
        return jnp.where(x > t, 1, jnp.where(x < -t, 2, 0)).astype(
            jnp.uint32)

    def decode(self, codes: jnp.ndarray) -> jnp.ndarray:
        t = self.threshold
        return jnp.where(codes == 1, t,
                         jnp.where(codes == 2, -t, 0.0)).astype(jnp.float32)

    # -- wire packing ------------------------------------------------------
    def pack(self, x_flat: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
        """fp32 [n] -> (uint32 [ceil(n/16)], n).  16 codes per word."""
        n = x_flat.shape[0]
        codes = self.codes(x_flat)
        pad = (-n) % 16
        codes = jnp.pad(codes, (0, pad))
        codes = codes.reshape(-1, 16)
        shifts = (2 * jnp.arange(16, dtype=jnp.uint32))[None, :]
        return jnp.sum(codes << shifts, axis=1, dtype=jnp.uint32), n

    def unpack(self, packed: jnp.ndarray, n: int) -> jnp.ndarray:
        shifts = (2 * jnp.arange(16, dtype=jnp.uint32))[None, :]
        codes = (packed[:, None] >> shifts) & jnp.uint32(3)
        return self.decode(codes.reshape(-1)[:n])

    # -- error-feedback push path -----------------------------------------
    def compress(self, key: str, grad: jnp.ndarray) -> Tuple[jnp.ndarray,
                                                             int]:
        """grad + residual -> quantized wire words; residual keeps the
        quantization error for the next round."""
        flat = grad.reshape(-1).astype(jnp.float32)
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros_like(flat)
        acc = flat + res
        q = self.quantize(acc)
        self._residuals[key] = acc - q
        return self.pack(acc)

    def residual(self, key: str) -> Optional[jnp.ndarray]:
        return self._residuals.get(key)
