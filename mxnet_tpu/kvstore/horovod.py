"""Horovod / BytePS KVStore adapters (reference
``python/mxnet/kvstore/horovod.py``, ``byteps.py``).

When the external package is installed, calls map straight onto it
(broadcast→hvd.broadcast, pushpull→hvd.allreduce).  When it is NOT —
the normal case on TPU pods — the same API runs on this framework's own
XLA collectives: ``jax.distributed`` ranks from the launcher env and a
single psum-shaped cross-process sum (`kvstore.py::_cross_process_sum`).
So ``kvstore='horovod'`` code trains unchanged, single- or
multi-process, with ICI/DCN collectives doing the reduction — the
TPU-first answer rather than an import error.
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["Horovod", "BytePS"]


class _XlaCollectives:
    """horovod-shaped rank/size/allreduce/broadcast over XLA collectives.

    Rank/size come from ``jax.distributed`` (initialized from the
    launcher's MXNET_TPU_* env when present; single-process otherwise).
    """

    def __init__(self):
        from . import kvstore_server

        kvstore_server.init_distributed()      # no-op without launcher env

    @staticmethod
    def rank() -> int:
        import jax

        return jax.process_index()

    @staticmethod
    def size() -> int:
        import jax

        return jax.process_count()

    @staticmethod
    def _local_sum(value):
        """A list value (one grad per local device, Trainer's
        ``param.list_grad()``) reduces locally first, like KVStoreLocal's
        Comm, before the cross-process collective."""
        import jax.numpy as jnp

        vals = value if isinstance(value, (list, tuple)) else [value]
        arrs = [v._data if hasattr(v, "_data") else jnp.asarray(v)
                for v in vals]
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out

    def allreduce_sum(self, value):
        from .kvstore import _cross_process_sum

        return _cross_process_sum(self._local_sum(value))

    def broadcast0(self, value):
        """Root-0 broadcast as ONE collective: non-root ranks contribute
        an explicit zeros buffer (NOT value * mask — non-root buffers are
        don't-care and may hold inf/nan, which a multiply would poison)."""
        import jax.numpy as jnp

        from .kvstore import _cross_process_sum

        first = value[0] if isinstance(value, (list, tuple)) else value
        x = first._data if hasattr(first, "_data") else jnp.asarray(first)
        if self.size() == 1:
            return x
        contribution = x if self.rank() == 0 else jnp.zeros_like(x)
        return _cross_process_sum(contribution)


def _copy_result(result, out):
    """Write ``result`` into every destination with ``copyto`` semantics
    (dtype cast + device placement follow the DESTINATION, exactly like
    the hvd-installed path's ``value.copyto(o)``)."""
    from ..context import current_context
    from ..ndarray.ndarray import NDArray, _wrap

    src = result if isinstance(result, NDArray) \
        else _wrap(result, current_context())
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        src.copyto(o)


def _try_import(modname):
    try:
        return __import__(modname, fromlist=["mxnet"])
    except ImportError:
        return None


@KVStoreBase.register
class Horovod(KVStoreBase):
    def __init__(self):
        self._hvd = _try_import("horovod.mxnet")
        if self._hvd is not None:
            # init errors from an INSTALLED horovod must surface, not
            # silently degrade to the fallback
            self._hvd.init()
            self._fallback = None
        else:
            self._fallback = _XlaCollectives()

    @property
    def type(self):
        return "horovod"

    @property
    def rank(self):
        return self._hvd.rank() if self._hvd else self._fallback.rank()

    @property
    def num_workers(self):
        return self._hvd.size() if self._hvd else self._fallback.size()

    @staticmethod
    def is_capable(capability):
        return False  # no server-side optimizer

    def broadcast(self, key, value, out, priority=0):
        if self._hvd:
            value = self._hvd.broadcast(value, root_rank=0, name=str(key))
            _copy_result(value, out)
            return
        _copy_result(self._fallback.broadcast0(value), out)

    def pushpull(self, key, value, out=None, priority=0):
        if self._hvd:
            summed = self._hvd.allreduce(value, average=False,
                                        name=str(key))
            _copy_result(summed, out if out is not None else value)
            return
        summed = self._fallback.allreduce_sum(value)
        # out=None means in-place allreduce into `value` (reference
        # horovod.py calls hvd.allreduce_(v) in place)
        _copy_result(summed, out if out is not None else value)


@KVStoreBase.register
class BytePS(KVStoreBase):
    """BytePS adapter; same fallback story as :class:`Horovod`."""

    def __init__(self):
        self._bps = _try_import("byteps.mxnet")
        if self._bps is not None:
            self._bps.init()
            self._fallback = None
        else:
            self._fallback = _XlaCollectives()

    @property
    def type(self):
        return "byteps"

    @property
    def rank(self):
        return self._bps.rank() if self._bps else self._fallback.rank()

    @property
    def num_workers(self):
        return self._bps.size() if self._bps else self._fallback.size()

    @staticmethod
    def is_capable(capability):
        return False

    def broadcast(self, key, value, out, priority=0):
        if self._bps:
            self._bps.byteps_declare_tensor(str(key))
            self._bps.byteps_push_pull(value, name=str(key),
                                       is_average=False)
            _copy_result(value, out)
            return
        _copy_result(self._fallback.broadcast0(value), out)

    def pushpull(self, key, value, out=None, priority=0):
        if self._bps:
            self._bps.byteps_push_pull(value, name=str(key),
                                       is_average=False)
            if out is not None:
                _copy_result(value, out)
            return
        summed = self._fallback.allreduce_sum(value)
        _copy_result(summed, out if out is not None else value)