"""Horovod KVStore adapter (reference ``python/mxnet/kvstore/horovod.py``).

Kept for API parity: maps broadcast→hvd.broadcast, pushpull→hvd.allreduce.
On TPU pods the native 'tpu' store (XLA collectives over ICI/DCN) is the
recommended backend; this adapter requires a horovod install with an
alltoall-capable backend.
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["Horovod"]


@KVStoreBase.register
class Horovod(KVStoreBase):
    def __init__(self):
        try:
            import horovod.mxnet as hvd  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "kvstore='horovod' requires the horovod package; on TPU use "
                "kvstore='tpu' (XLA collectives) instead"
            ) from e
        import horovod.mxnet as hvd

        self._hvd = hvd
        hvd.init()

    @property
    def type(self):
        return "horovod"

    @property
    def rank(self):
        return self._hvd.rank()

    @property
    def num_workers(self):
        return self._hvd.size()

    @staticmethod
    def is_capable(capability):
        return False  # no server-side optimizer

    def broadcast(self, key, value, out, priority=0):
        value = self._hvd.broadcast(value, root_rank=0, name=str(key))
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            value.copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        summed = self._hvd.allreduce(value, average=False, name=str(key))
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                summed.copyto(o)


@KVStoreBase.register
class BytePS(KVStoreBase):
    """BytePS adapter (reference ``python/mxnet/kvstore/byteps.py``)."""

    def __init__(self):
        try:
            import byteps.mxnet as bps  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "kvstore='byteps' requires the byteps package; on TPU use "
                "kvstore='tpu' (XLA collectives) instead"
            ) from e
        import byteps.mxnet as bps

        self._bps = bps
        bps.init()

    @property
    def type(self):
        return "byteps"

    @property
    def rank(self):
        return self._bps.rank()

    @property
    def num_workers(self):
        return self._bps.size()

    @staticmethod
    def is_capable(capability):
        return False

    def broadcast(self, key, value, out, priority=0):
        self._bps.byteps_declare_tensor(str(key))
        self._bps.byteps_push_pull(value, name=str(key), is_average=False)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            value.copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        self._bps.byteps_push_pull(value, name=str(key), is_average=False)
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                value.copyto(o)
