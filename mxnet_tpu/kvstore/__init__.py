"""KVStore: distributed key-value parameter synchronization.

Reference ``src/kvstore/`` + ``python/mxnet/kvstore/``.  Factory semantics
mirror ``KVStore::Create`` (src/kvstore/kvstore.cc:42-80): string type picks
the backend.  TPU mapping:

- 'local'/'device' → single-process replica reduce (CommCPU/CommDevice analog)
- 'tpu'/'nccl'     → same API, collectives ride ICI; on multi-controller
  launches the reduce crosses DCN (NCCL/ps-lite analog)
- 'dist_sync'/'dist_device_sync'/'dist_async' → multi-controller 'tpu'
  (synchronous; async parameter-server semantics collapse to sync on TPU's
  SPMD model)
- 'horovod'/'byteps' → adapters (require those packages)
"""
from .base import KVStoreBase
from .kvstore import KVStore
from . import horovod as _adapters  # registers Horovod/BytePS

__all__ = ["KVStoreBase", "KVStore", "create"]


def create(name="local"):
    """Create a KVStore by type string (reference kvstore.py:743 create /
    KVStore::Create kvstore.cc:42)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    lname = name.lower()
    if lname in ("local", "device", "tpu", "nccl", "local_allreduce_cpu",
                 "local_allreduce_device"):
        return KVStore("tpu" if lname in ("tpu", "nccl") else lname)
    if lname.startswith("dist") or lname.startswith("p3"):
        # dist_sync / dist_device_sync / p3 variants: multi-controller
        # synchronous collectives over DCN.  dist_async routes pushes
        # through a per-process pipeline thread (overlap, no caller
        # blocking); p3-style priority/bucketing is the list-push fusion.
        if "async" in lname:
            return KVStore("dist_async")
        return KVStore("dist_sync")
    if lname in KVStoreBase.kv_registry:
        return KVStoreBase.kv_registry[lname]()
    raise ValueError(f"unknown KVStore type {name}")
