"""KVStore base + plugin registry (reference
``python/mxnet/kvstore/base.py:74-245``)."""
from __future__ import annotations

from typing import Dict

__all__ = ["KVStoreBase"]


class KVStoreBase:
    """Key-value store interface for parameter synchronization.

    Backends register by name (``KVStoreBase.register``), mirroring the
    reference's plugin registry that lets Horovod/BytePS slot in beside the
    native stores.
    """

    kv_registry: Dict[str, type] = {}

    OPTIMIZER = "optimizer"

    # -- interface -------------------------------------------------------
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):
        raise NotImplementedError

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError

    @property
    def type(self):
        # registered name (reference kv.type == 'teststore' for a custom
        # plugin class TestStore); plugins may override
        return type(self).__name__.lower()

    @property
    def rank(self):
        raise NotImplementedError

    @property
    def num_workers(self):
        raise NotImplementedError

    # -- registry --------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        if name in KVStoreBase.kv_registry:
            import logging

            logging.warning("KVStore %s overridden", name)
        KVStoreBase.kv_registry[name] = klass
        return klass
