"""Single-process KVStore: 'local' / 'device' / 'tpu'.

TPU-native re-design of the reference comm stack (SURVEY.md §2.3):

- ``KVStoreLocal`` + ``CommCPU``/``CommDevice`` (``src/kvstore/kvstore_local.h``,
  ``src/kvstore/comm.h``): per-key reduce over device replicas + broadcast
  back.  Here the reduce is one XLA computation (``add_n``) per key — XLA
  owns the scheduling that the reference's dependency engine provided.
- ``KVStoreNCCL``/``CommDeviceTree``: topology-aware collectives.  On TPU the
  analog is ICI all-reduce; for the eager per-key path this store computes
  the reduction on-device, while the *sharded* training path
  (``mxnet_tpu.parallel``) folds the same all-reduce into the compiled step
  as ``lax.psum`` riding ICI — that path replaces NCCL rings entirely.
- ``KVStoreDist`` (ps-lite parameter server): multi-host sync is an XLA
  collective over DCN in the sharded path; the eager path cross-process
  reduces via jax multihost allgather when launched multi-controller
  (``mxnet_tpu.kvstore.launch`` analog of tools/launch.py).
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap
from .base import KVStoreBase

__all__ = ["KVStore"]


@KVStoreBase.register
class KVStore(KVStoreBase):
    """Single-controller store over the local devices ('local'/'device'/'tpu'
    all resolve here; 'tpu' additionally cross-process reduces when run
    multi-controller)."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._data: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0

    # -- identity --------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @staticmethod
    def is_capable(capability):
        if capability.lower() == KVStoreBase.OPTIMIZER:
            return True
        return False

    # -- init / push / pull ---------------------------------------------
    def _str_key(self, key):
        return str(key)

    def init(self, key, value):
        """Initialize (key, value) pairs (reference kvstore.py init)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            self._data[k] = v[0].copy()

    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            keys = [self._str_key(k) for k in key]
            values = [v if isinstance(v, (list, tuple)) else [v] for v in value]
        else:
            keys = [self._str_key(key)]
            values = [value if isinstance(value, (list, tuple)) else [value]]
        return keys, values

    def broadcast(self, key, value, out, priority=0):
        """Init + pull in one call (reference base.py broadcast)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._data:
                self._data[k] = v[0].copy()
        self.pull(key, out=out, priority=priority)

    def _reduce(self, value_list: List[NDArray]) -> jnp.ndarray:
        """Sum replicas — one fused XLA computation (CommDevice::Reduce
        analog, comm.h:504)."""
        if len(value_list) == 1:
            merged = value_list[0]._data
        else:
            merged = value_list[0]._data
            for v in value_list[1:]:
                merged = merged + jax.device_put(v._data, merged.devices().pop())
        if self._type.startswith("dist") or (
            self._type == "tpu" and jax.process_count() > 1
        ):
            # cross-process sum over DCN (KVStoreDist analog)
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(merged)
            merged = jnp.sum(gathered, axis=0)
        return merged

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            merged = self._reduce(v)
            if self._updater is not None:
                if k not in self._data:
                    self._data[k] = _wrap(jnp.zeros_like(merged), v[0].ctx)
                self._updater(_key_int(k), _wrap(merged, v[0].ctx), self._data[k])
            else:
                self._data[k] = _wrap(merged, v[0].ctx)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, _ = self._normalize(key, out)
        outs = out if isinstance(out, (list, tuple)) else [out]
        if isinstance(key, (list, tuple)):
            grouped = outs
        else:
            grouped = [outs]
        for k, group in zip(keys, grouped):
            if k not in self._data:
                raise KeyError(f"key {k} has not been initialized in KVStore")
            src = self._data[k]
            dsts = group if isinstance(group, (list, tuple)) else [group]
            for d in dsts:
                d._set_data(
                    jax.device_put(src._data, d._data.devices().pop()).astype(
                        d._data.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference KVStoreLocal::PushPullImpl,
        kvstore_local.h:358)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # -- server-side optimizer ------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import Updater

        self._optimizer = optimizer
        self._updater = Updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "There is no optimizer in the store"
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "There is no optimizer in the store"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- misc ------------------------------------------------------------
    def barrier(self):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"mxnet_tpu_kvstore_barrier_{self._barrier_count}")
            self._barrier_count += 1


def _key_int(k: str):
    try:
        return int(k)
    except ValueError:
        return k
