"""Single-process KVStore: 'local' / 'device' / 'tpu'.

TPU-native re-design of the reference comm stack (SURVEY.md §2.3):

- ``KVStoreLocal`` + ``CommCPU``/``CommDevice`` (``src/kvstore/kvstore_local.h``,
  ``src/kvstore/comm.h``): per-key reduce over device replicas + broadcast
  back.  Here the reduce is one XLA computation (``add_n``) per key — XLA
  owns the scheduling that the reference's dependency engine provided.
- ``KVStoreNCCL``/``CommDeviceTree``: topology-aware collectives.  On TPU the
  analog is ICI all-reduce; for the eager per-key path this store computes
  the reduction on-device, while the *sharded* training path
  (``mxnet_tpu.parallel``) folds the same all-reduce into the compiled step
  as ``lax.psum`` riding ICI — that path replaces NCCL rings entirely.
- ``KVStoreDist`` (ps-lite parameter server): multi-host sync is an XLA
  collective over DCN in the sharded path; the eager path cross-process
  reduces via jax multihost allgather when launched multi-controller
  (``mxnet_tpu.kvstore.launch`` analog of tools/launch.py).
"""
from __future__ import annotations

import pickle
import queue
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import config as _config
from .. import faults as _faults
from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap
from ..ndarray.sparse import RowSparseNDArray
from .base import KVStoreBase

__all__ = ["KVStore"]


def _one_device_per_process():
    per = {}
    for d in jax.devices():
        per.setdefault(d.process_index, d)
    return [per[i] for i in range(jax.process_count())]


_PROC_MESH = None          # (mesh, in_sharding, jitted sum) — built once
_SUM_FN = None


def _proc_mesh():
    global _PROC_MESH, _SUM_FN
    if _PROC_MESH is None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        devs = _one_device_per_process()
        mesh = Mesh(onp.array(devs), ("p",))
        _PROC_MESH = (mesh, NamedSharding(mesh, PartitionSpec("p")))
        _SUM_FN = jax.jit(lambda a: jnp.sum(a, axis=0),
                          out_shardings=NamedSharding(mesh, PartitionSpec()))
    return _PROC_MESH


def _cross_process_sum(x: jnp.ndarray) -> jnp.ndarray:
    """All-reduce over processes as ONE XLA collective (psum over a
    process mesh), replacing round 1's allgather-then-host-sum: O(size)
    DCN bandwidth instead of O(P * size) host traffic (reference analog:
    server-side aggregation in kvstore_dist_server.h:346).  The process
    mesh and jitted sum are module-level so every push hits jax's trace
    cache (keyed by shape/dtype only)."""
    P = jax.process_count()
    if P == 1:
        return x
    mesh, in_sh = _proc_mesh()
    mine = _one_device_per_process()[jax.process_index()]
    local = jax.device_put(jnp.expand_dims(x, 0), mine)
    garr = jax.make_array_from_single_device_arrays(
        (P,) + tuple(x.shape), in_sh, [local])
    try:
        return _SUM_FN(garr).addressable_data(0)
    except jax.errors.JaxRuntimeError:
        # this jaxlib's CPU backend rejects multiprocess XLA computations
        # outright ("Multiprocess computations aren't implemented on the
        # CPU backend"); fall back to an allgather-then-sum over the
        # jax.distributed key-value service — O(P * size) host traffic,
        # acceptable on the CPU test rig; real deployments (tpu) never
        # take this branch
        return _kv_allgather_sum(x)


_KV_GATHER_SEQ = 0


def _kv_allgather(x) -> onp.ndarray:
    """Allgather over the jax.distributed key-value service, under the
    shared retry policy (site ``kvstore.collective``): a transient kv-
    service failure re-runs the WHOLE gather with a fresh sequence number
    (the per-seq key namespace makes a replay collision-free), with
    exponential backoff between attempts."""
    return _faults.retry_call(_kv_allgather_once, x,
                              site="kvstore.collective")


def _kv_allgather_once(x) -> onp.ndarray:
    """One allgather attempt (host path): each rank publishes its buffer,
    every rank fetches all of them; a trailing round of 'done' keys keeps
    payloads alive until every rank has read them.  Fallback for backends
    whose compiler rejects multiprocess XLA computations (this jaxlib's
    CPU runtime); real deployments (tpu) reduce over ICI/DCN collectives
    instead."""
    global _KV_GATHER_SEQ
    from jax._src import distributed

    from ..base import MXNetError

    client = distributed.global_state.client
    if client is None:
        raise MXNetError(
            "cross-process reduce unavailable: multiprocess XLA "
            "computations unsupported on this backend and jax.distributed "
            "is not initialized")
    seq, _KV_GATHER_SEQ = _KV_GATHER_SEQ, _KV_GATHER_SEQ + 1
    rank, nproc = jax.process_index(), jax.process_count()
    host = onp.ascontiguousarray(onp.asarray(x))
    client.key_value_set_bytes(f"mxtpu_ag/{seq}/{rank}", host.tobytes())
    parts = []
    for r in range(nproc):
        raw = client.blocking_key_value_get_bytes(
            f"mxtpu_ag/{seq}/{r}", 120_000)
        parts.append(onp.frombuffer(raw, host.dtype).reshape(host.shape))
    client.key_value_set(f"mxtpu_ag_done/{seq}/{rank}", "1")
    for r in range(nproc):
        client.blocking_key_value_get(f"mxtpu_ag_done/{seq}/{r}", 120_000)
    if rank == 0:
        client.key_value_delete(f"mxtpu_ag/{seq}/")
    return onp.stack(parts)


def _kv_allgather_sum(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.asarray(_kv_allgather(x).sum(axis=0).astype(onp.asarray(x).dtype))


@KVStoreBase.register
class KVStore(KVStoreBase):
    """Single-controller store over the local devices ('local'/'device'/'tpu'
    all resolve here; 'tpu' additionally cross-process reduces when run
    multi-controller)."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._data: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        self._compression = None
        self._heartbeat = None   # attach_heartbeat(): names dead ranks on
        # barrier deadline (parallel/elastic.py HeartbeatMonitor)
        # dist_async: pushes are applied by a dedicated worker thread (the
        # reference's server-side request queue, kvstore_dist_server.h exec_
        # serial executor) so the caller overlaps compute with comm; every
        # process drains the same key order, keeping collectives aligned
        self._async_q: Optional[queue.Queue] = None
        self._async_err: List[BaseException] = []
        if kv_type == "dist_async":
            self._async_q = queue.Queue()
            t = threading.Thread(target=self._async_worker,
                                 args=(self._async_q,), daemon=True)
            t.start()
            # the async push queue is outstanding host-side work:
            # engine.waitall() / the preemption drain must flush it like
            # every other async stage (graftlint thread-discipline), so
            # a drained checkpoint can never miss an applied push
            from .. import engine as _engine

            _engine.register_drainable(self)

    # -- identity --------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @staticmethod
    def is_capable(capability):
        if capability.lower() == KVStoreBase.OPTIMIZER:
            return True
        return False

    # -- init / push / pull ---------------------------------------------
    def _str_key(self, key):
        return str(key)

    def init(self, key, value):
        """Initialize (key, value) pairs (reference kvstore.py init).

        RowSparseNDArray values are densified on entry: the TPU store is
        dense-backed (HBM + XLA gather/scatter make dense rows the fast
        path), with ``row_sparse_pull`` preserving the sparse-pull API.
        """
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            first = v[0]
            if isinstance(first, RowSparseNDArray):
                self._data[k] = first.todense()
            else:
                self._data[k] = first.copy()

    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            keys = [self._str_key(k) for k in key]
            values = [v if isinstance(v, (list, tuple)) else [v] for v in value]
        else:
            keys = [self._str_key(key)]
            values = [value if isinstance(value, (list, tuple)) else [value]]
        return keys, values

    def broadcast(self, key, value, out, priority=0):
        """Init + pull in one call (reference base.py broadcast)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._data:
                first = v[0]
                self._data[k] = (first.todense()
                                 if isinstance(first, RowSparseNDArray)
                                 else first.copy())
        self.pull(key, out=out, priority=priority)

    def _is_dist(self) -> bool:
        return (self._type.startswith("dist")
                or (self._type == "tpu" and jax.process_count() > 1))

    def _local_sum(self, value_list: List[NDArray]) -> jnp.ndarray:
        merged = value_list[0]._data
        for v in value_list[1:]:
            merged = merged + jax.device_put(v._data,
                                             merged.devices().pop())
        return merged

    def _reduce(self, key: str, value_list: List[NDArray]) -> jnp.ndarray:
        """Sum replicas — one fused XLA computation (CommDevice::Reduce
        analog, comm.h:504) — then, for dist stores, one cross-process
        psum collective (or a 16x-smaller allgather of 2-bit codes when
        gradient compression is on)."""
        merged = self._local_sum(value_list)
        if not self._is_dist():
            return merged
        if self._compression is not None:
            # worker-side 2-bit quantization with error feedback before
            # the wire (reference gradient_compression.h:38): each rank
            # ships packed codes, every rank decodes+sums all ranks
            from jax.experimental import multihost_utils

            packed, n = self._compression.compress(key, merged)
            try:
                gathered = multihost_utils.process_allgather(packed)
            except jax.errors.JaxRuntimeError:
                # CPU runtime rejects multiprocess XLA computations; ship
                # the codes over the jax.distributed kv service instead
                gathered = jnp.asarray(_kv_allgather(packed))
            decoded = sum(
                self._compression.unpack(gathered[r], n)
                for r in range(gathered.shape[0]))
            return decoded.reshape(merged.shape).astype(merged.dtype)
        return _cross_process_sum(merged)

    def _apply_merged(self, k: str, merged: jnp.ndarray, ctx) -> None:
        if self._updater is not None:
            if k not in self._data:
                self._data[k] = _wrap(jnp.zeros_like(merged), ctx)
            self._updater(_key_int(k), _wrap(merged, ctx), self._data[k])
        else:
            self._data[k] = _wrap(merged, ctx)

    # -- dist_async pipeline ---------------------------------------------
    def _async_worker(self, q):
        # the queue is passed in (not re-read from self) so close() can
        # null the attribute without racing this loop
        while True:
            item = q.get()
            if item is None:
                q.task_done()
                return
            k, v = item
            try:
                if isinstance(v[0], RowSparseNDArray):
                    self._push_row_sparse(k, v)
                else:
                    self._apply_merged(k, self._reduce(k, v), v[0].ctx)
            except BaseException as e:          # surfaced at next sync
                self._async_err.append(e)
            finally:
                q.task_done()

    def _drain_async(self):
        if self._async_q is not None:
            self._async_q.join()
            if self._async_err:
                raise self._async_err.pop(0)

    # engine.waitall() drains registered dist_async stores: every queued
    # push applied, absorbed worker errors re-raised at the wait point
    drain = _drain_async

    def close(self):
        """Stop the dist_async pipeline thread (idempotent); surfaces any
        pending async push errors."""
        q, self._async_q = self._async_q, None
        if q is not None:
            q.join()
            q.put(None)                      # worker exits on sentinel
            q.join()
            if self._async_err:
                raise self._async_err.pop(0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def push(self, key, value, priority=0):
        """Push values.  List pushes on a dist store are bucketed: all
        same-dtype keys fuse into ONE flattened cross-process collective
        (the P3 bucketing/priority analog, p3store_dist.h:40 — higher
        ``priority`` keys are simply pushed first by callers)."""
        # fail-fast injection point: a push may apply a server-side
        # optimizer update, so replaying a half-applied push is NOT
        # idempotent — faults here propagate (docs/ROBUSTNESS.md taxonomy)
        _faults.inject("kvstore.push")
        keys, values = self._normalize(key, value)
        if self._async_q is not None:
            for k, v in zip(keys, values):
                # snapshot the immutable jax buffers NOW — the caller may
                # overwrite its NDArrays (grad[:]=0) before the worker
                # thread dequeues; RowSparseNDArrays re-wrap their (data,
                # indices) buffers for the same reason
                snap = [RowSparseNDArray(x.data, x.indices, x.shape, x.ctx)
                        if isinstance(x, RowSparseNDArray)
                        else _wrap(x._data, x.ctx) for x in v]
                self._async_q.put((k, snap))
            return
        if any(isinstance(v[0], RowSparseNDArray) for v in values):
            for k, v in zip(keys, values):
                if isinstance(v[0], RowSparseNDArray):
                    self._push_row_sparse(k, v)
                else:
                    self._apply_merged(k, self._reduce(k, v), v[0].ctx)
            return
        if len(keys) > 1 and self._updater is not None \
                and self._compression is None \
                and self._push_fused_update(keys, values):
            return
        if (len(keys) > 1 and self._is_dist()
                and self._compression is None and self._updater is None):
            self._push_bucketed(keys, values)
            return
        for k, v in zip(keys, values):
            self._apply_merged(k, self._reduce(k, v), v[0].ctx)

    def _push_fused_update(self, keys, values) -> bool:
        """Server-side fused optimizer update: reduce every key, then apply
        the optimizer over the WHOLE key set in one updater call — the
        optimizer groups the keys and updates each group as one compiled
        program (optimizer/fused.py), replacing the per-key updater loop
        the reference server ran (kvstore_dist_server.h:346)."""
        from ..optimizer import Updater
        from ..optimizer import fused as _fused

        if self._optimizer is None or not _fused.enabled(self._optimizer) \
                or not isinstance(self._updater, Updater):
            # custom set_updater callables keep the per-key calling
            # convention — only the real Updater understands list calls
            return False
        merged = [self._reduce(k, v) for k, v in zip(keys, values)]
        for k, m, v in zip(keys, merged, values):
            if k not in self._data:
                self._data[k] = _wrap(jnp.zeros_like(m), v[0].ctx)
        self._updater(
            [_key_int(k) for k in keys],
            [_wrap(m, v[0].ctx) for m, v in zip(merged, values)],
            [self._data[k] for k in keys])
        return True

    def _push_row_sparse(self, k: str, value_list) -> None:
        """Sparse push: replica reduce = index concat + ``compact()`` (the
        reference's row-sparse merge, ``src/kvstore/comm.h`` sparse branch
        of CommCPU::Reduce).  Only the touched rows are materialized until
        the final apply; dist stores ship the DENSE merged gradient over
        the collective (documented trade-off: XLA collectives are dense —
        the reference's ``EncodeRowSparseKey`` wire format has no ICI
        analog, and embedding-gradient rows are a minority of step time).
        """
        merged = value_list[0]
        for v in value_list[1:]:
            merged = merged + v                 # O(nnz) index/data concat
        merged = merged.compact()
        ctx = merged.ctx
        dense = merged.todense()._data
        if self._is_dist():
            dense = _cross_process_sum(dense)
        if self._updater is not None:
            # dense-apply: rows outside ``indices`` carry zero gradient, so
            # plain sgd leaves them untouched; decoupled-wd optimizers decay
            # every row (the documented dense semantics of this backend)
            if k not in self._data:
                self._data[k] = _wrap(jnp.zeros_like(dense), ctx)
            self._updater(_key_int(k), _wrap(dense, ctx), self._data[k])
        else:
            self._data[k] = _wrap(dense, ctx)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in ``row_ids`` (reference
        ``python/mxnet/kvstore/kvstore.py:420``).  The store's value is
        dense in HBM; this gathers the requested rows on-device and writes
        ``RowSparseNDArray`` outs (dense outs receive a masked dense copy:
        requested rows live, others zero).  ``row_ids`` may be one array
        shared by every out, or a list matching ``out`` one-to-one.
        """
        self._drain_async()
        if out is None:
            raise ValueError("row_sparse_pull requires out=")
        if row_ids is None:
            raise ValueError("row_sparse_pull requires row_ids=")
        # flatten to one (key, out, row_ids) triple per destination: a
        # row_ids LIST matches the out list one-to-one even for a single
        # key; a single row_ids array is shared by every out
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        if isinstance(key, (list, tuple)):
            keys = [self._str_key(k) for k in key]
            if len(keys) != len(outs):
                raise ValueError("key list and out list lengths differ")
        else:
            keys = [self._str_key(key)] * len(outs)
        if isinstance(row_ids, (list, tuple)):
            rids = list(row_ids)
            if len(rids) != len(outs):
                raise ValueError("row_ids list must match out one-to-one")
        else:
            rids = [row_ids] * len(outs)
        for k, d, rid in zip(keys, outs, rids):
            if k not in self._data:
                raise KeyError(f"key {k} has not been initialized in KVStore")
            src = self._data[k]._data
            ids = jnp.asarray(
                rid._data if isinstance(rid, NDArray) else rid,
                jnp.int32).reshape(-1)
            ids = jnp.unique(ids)               # reference sorts + dedups
            rows = jnp.take(src, ids, axis=0)
            if isinstance(d, RowSparseNDArray):
                dev = (d.data.devices().pop()
                       if isinstance(d.data, jax.Array) else None)
                d.data = (jax.device_put(rows, dev) if dev else rows)
                d.indices = (jax.device_put(ids, dev) if dev else ids)
            else:
                masked = jnp.zeros_like(src).at[ids].set(rows)
                d._set_data(jax.device_put(
                    masked, d._data.devices().pop()).astype(
                        d._data.dtype))

    def _push_bucketed(self, keys, values):
        """Fuse many keys into flat cross-process sums.  Arrays above
        MXNET_KVSTORE_BIGARRAY_BOUND get their own collective (reference
        kvstore_dist big-array splitting; see mxnet_tpu.config)."""
        bound = _config.get("MXNET_KVSTORE_BIGARRAY_BOUND")
        locals_ = [self._local_sum(v) for v in values]
        buckets: Dict[str, List[int]] = {}
        for i, m in enumerate(locals_):
            if m.size > bound:
                buckets[f"big{i}"] = [i]
            else:
                buckets.setdefault(str(m.dtype), []).append(i)
        for _bk, idxs in buckets.items():
            flat = jnp.concatenate([locals_[i].reshape(-1) for i in idxs]) \
                if len(idxs) > 1 else locals_[idxs[0]].reshape(-1)
            summed = _cross_process_sum(flat)
            off = 0
            for i in idxs:
                size = locals_[i].size
                part = summed[off:off + size].reshape(locals_[i].shape)
                off += size
                self._data[keys[i]] = _wrap(part, values[i][0].ctx)

    def set_gradient_compression(self, compression_params):
        """Enable worker-side gradient compression for dist pushes
        (reference kvstore.py set_gradient_compression ->
        GradientCompression, src/kvstore/gradient_compression.cc)."""
        from .compression import GradientCompression

        params = dict(compression_params)
        ctype = params.pop("type", params.pop("compression", "2bit"))
        self._compression = GradientCompression(type=ctype, **params)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Pull current values into ``out``.  Pulls are pure reads of the
        store (outs are fully rewritten on success), so a transient
        failure retries the whole pull under the shared policy (site
        ``kvstore.pull``)."""
        self._drain_async()
        _faults.retry_call(self._pull_impl, key, out, site="kvstore.pull")

    def _pull_impl(self, key, out):
        keys, _ = self._normalize(key, out)
        outs = out if isinstance(out, (list, tuple)) else [out]
        if isinstance(key, (list, tuple)):
            grouped = outs
        else:
            grouped = [outs]
        for k, group in zip(keys, grouped):
            if k not in self._data:
                raise KeyError(f"key {k} has not been initialized in KVStore")
            src = self._data[k]
            dsts = group if isinstance(group, (list, tuple)) else [group]
            for d in dsts:
                d._set_data(
                    jax.device_put(src._data, d._data.devices().pop()).astype(
                        d._data.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference KVStoreLocal::PushPullImpl,
        kvstore_local.h:358)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # -- server-side optimizer ------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import Updater

        self._optimizer = optimizer
        self._updater = Updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "There is no optimizer in the store"
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "There is no optimizer in the store"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- misc ------------------------------------------------------------
    def attach_heartbeat(self, monitor) -> None:
        """Attach a ``parallel.elastic.HeartbeatMonitor`` so a barrier
        deadline breach can NAME the suspected-dead ranks instead of
        hanging anonymously (the reference's ps-lite node heartbeats,
        never surfaced to users — SURVEY §5)."""
        self._heartbeat = monitor

    def barrier(self, timeout: Optional[float] = None):
        """Global barrier with an optional deadline.  ``timeout`` (or
        ``MXNET_BARRIER_TIMEOUT``; 0 = wait forever) bounds the wait; on
        breach raises :class:`faults.DeadlineExceeded` listing the ranks
        whose heartbeat went stale (when a monitor is attached via
        :meth:`attach_heartbeat`).  The underlying collective cannot be
        cancelled — the sync thread is left behind as a daemon, and the
        caller is expected to checkpoint-and-exit (run_elastic restarts
        absorb the loss)."""
        self._drain_async()
        _faults.inject("kvstore.barrier")
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils

        name = f"mxnet_tpu_kvstore_barrier_{self._barrier_count}"
        if timeout is None:
            timeout = _config.get("MXNET_BARRIER_TIMEOUT")
        if not timeout:
            multihost_utils.sync_global_devices(name)
            self._barrier_count += 1
            return
        done = threading.Event()
        err: List[BaseException] = []

        def _sync():
            try:
                multihost_utils.sync_global_devices(name)
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        # graftlint: daemon-ok(bounded barrier watchdog: outcome joined
        # via done.wait(timeout) right below; holds no queued work)
        threading.Thread(target=_sync, daemon=True,
                         name=f"kvstore-barrier-{self._barrier_count}").start()
        if not done.wait(timeout):
            suspects = (self._heartbeat.dead_ranks()
                        if self._heartbeat is not None else None)
            if suspects:
                who = f"suspected dead ranks: {suspects}"
                # a hung host converges on the SAME restart-time
                # exclusion mechanism as a corrupt one: the suspects
                # land in the sentinel's persisted quarantine list, and
                # the next mesh resolve excludes their devices
                from .. import sentinel as _sentinel

                _sentinel.quarantine_ranks(suspects,
                                           reason="barrier-timeout")
            elif self._heartbeat is not None:
                who = ("all heartbeats live — slow rank or network "
                       "partition")
            else:
                who = ("no HeartbeatMonitor attached "
                       "(KVStore.attach_heartbeat) — suspects unknown")
            _faults.record_event("kvstore.barrier", "deadline",
                                 timeout=timeout, suspects=suspects)
            raise _faults.DeadlineExceeded(
                f"barrier {self._barrier_count} timed out after {timeout}s "
                f"({jax.process_count()} processes); {who}")
        self._barrier_count += 1
        if err:
            raise err[0]


def _key_int(k: str):
    try:
        return int(k)
    except ValueError:
        return k
