"""Dynamic loss scaler (reference
``python/mxnet/contrib/amp/loss_scaler.py``).

Needed for fp16; optional for bf16 (same exponent range as fp32).  Scale
doubles every ``scale_window`` clean steps, halves on overflow, and the
overflowed step is skipped — identical policy to the reference.
"""
from __future__ import annotations

from ..ndarray import NDArray

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        """True if any gradient is non-finite (reference
        loss_scaler.py has_overflow).  The whole finiteness reduction runs
        as ONE compiled program (optimizer/fused.py all_finite) with
        exactly one scalar host sync per call."""
        import jax.numpy as jnp

        from ..optimizer import fused as _fused

        arrays = []
        for p in params:
            grads = p.list_grad() if hasattr(p, "list_grad") else [p]
            for g in grads:
                if g is None:
                    continue
                arrays.append(g._data if isinstance(g, NDArray)
                              else jnp.asarray(g))
        if not arrays:
            return False
        return not bool(_fused.all_finite(arrays))

    def branch_scales(self):
        """Preview ``(scale_if_clean, scale_if_overflow)`` — the scale
        the NEXT step would use under each verdict of the still-unread
        all-finite flag.  The deferred AMP gate (cached_step.TrainStep,
        MXNET_AMP_LAG) dispatches speculatively with BOTH candidates and
        lets the device select on the previous step's flag, so the host
        read lags one step while numerics stay bit-exact vs the
        synchronous gate.  Pure: mirrors :meth:`update_scale` without
        mutating state."""
        if self._unskipped + 1 >= self._scale_window:
            clean = self.loss_scale * self._scale_factor
        else:
            clean = self.loss_scale
        return clean, max(1.0, self.loss_scale / self._scale_factor)

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
