"""AMP op lists (reference ``python/mxnet/contrib/amp/lists/symbol_fp16.py``).

Three classes, same split logic as the reference:
- LOW_PRECISION_FUNCS: matmul/conv-class ops that are safe and fast in
  bf16/fp16 (MXU ops)
- FP32_FUNCS: numerically sensitive ops pinned to fp32 (norms, softmax/log,
  losses, reductions feeding statistics)
- WIDEST_TYPE_CASTS: elementwise multi-input ops that follow their widest
  input
On TPU the low-precision dtype is bfloat16 by default — same exponent range
as fp32, so the reference's loss-scaling machinery is optional (kept for
fp16 parity).
"""

LOW_PRECISION_FUNCS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "linalg_gemm", "linalg_gemm2", "_rnn_fused",
]

FP32_FUNCS = [
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "LRN",
    "L2Normalization", "softmax", "log_softmax", "softmin",
    "softmax_cross_entropy", "SoftmaxOutput", "CTCLoss", "MakeLoss",
    "exp", "log", "log2", "log10", "log1p", "expm1", "square", "sqrt",
    "rsqrt", "cbrt", "power", "norm", "mean", "sum", "prod", "nansum",
    "nanprod", "cumsum", "cumprod", "moments", "erf", "erfinv", "gamma",
    "gammaln",
]

WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_maximum",
    "broadcast_minimum", "broadcast_hypot", "add_n", "concat", "stack",
    "where", "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
]
