"""AMP op lists (reference ``python/mxnet/contrib/amp/lists/symbol_fp16.py``
— the reference classifies its whole operator surface into per-op lists;
this module does the same for this registry, enforced exhaustive by
tests/test_amp_profiler.py).

Four classes, same split logic as the reference:

- LOW_PRECISION_FUNCS (reference FP16_FUNCS): matmul/conv-class ops that
  are safe and fast in bf16/fp16 — these are the MXU ops, where low
  precision doubles throughput.
- FP32_FUNCS: numerically sensitive ops pinned to fp32 — norms, softmax /
  log / exp family, losses, statistics-feeding reductions, linear
  algebra factorizations, probability densities, and optimizer update
  kernels (master-weight math stays fp32).
- WIDEST_TYPE_CASTS: multi-input elementwise ops that follow their widest
  input dtype (reference WIDEST_TYPE_CASTS).
- FP16_FP32_FUNCS: dtype-neutral ops that run correctly in whichever
  precision arrives (moves/reshapes/indexing/comparisons/integer and
  random ops).  The policy leaves their inputs untouched.

On TPU the low-precision dtype is bfloat16 by default — same exponent
range as fp32, so the reference's loss-scaling machinery is optional
(kept for fp16 parity).
"""

LOW_PRECISION_FUNCS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "matmul", "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt", "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt", "linalg_gemm", "linalg_gemm2",
    "_rnn_fused", "DeformableConvolution", "ModulatedDeformableConvolution",
    # fused conv+BN (ops/nn.py): conv-dominated, classified LOW for the
    # registry-exhaustiveness contract, but amp/__init__.py::_policy has
    # a DEDICATED rule: conv operands (x, w, bias) cast down like
    # Convolution while the trailing gamma/beta stay fp32 like the
    # unfused BatchNorm (FP32_FUNCS) — parameter values and running
    # stats must not round
    "_fused_conv1x1_bn", "_fused_convkxk_bn",
    "_fused_conv1x1_bn_act",
    "Correlation", "khatri_rao",
]

FP32_FUNCS = [
    # normalization / losses
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "LRN",
    "L2Normalization", "softmax", "log_softmax", "softmin",
    "softmax_cross_entropy", "SoftmaxOutput", "CTCLoss", "MakeLoss",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "smooth_l1",
    "SyncBatchNorm", "BatchNormWithReLU", "hawkesll",
    # exp/log family and friends
    "exp", "log", "log2", "log10", "log1p", "expm1", "square", "sqrt",
    "rsqrt", "cbrt", "rcbrt", "power", "power_scalar", "reciprocal",
    "softrelu", "log_sigmoid", "mish", "erf", "erfinv", "gamma",
    "gammaln", "digamma", "hypot", "hypot_scalar", "ldexp", "logaddexp",
    "div_sqrt_dim", "quadratic",
    # statistics-feeding reductions
    "norm", "mean", "sum", "prod", "nansum", "nanprod", "cumsum",
    "cumprod", "moments", "multi_sum_sq", "linalg_sumlogdiag",
    # sensitive inverse-trig / hyperbolic
    "arccos", "arcsin", "arctan", "arccosh", "arcsinh", "arctanh",
    "degrees", "radians",
    # linear-algebra factorizations / solves
    "linalg_cholesky", "linalg_potrf", "linalg_potri", "linalg_det",
    "linalg_slogdet", "linalg_inverse", "linalg_pinv", "linalg_eigh",
    "linalg_eigvalsh", "linalg_svd", "linalg_qr", "linalg_gelqf",
    "linalg_lstsq", "linalg_solve", "linalg_trmm", "linalg_trsm",
    "linalg_syrk", "linalg_tensorinv", "linalg_matrix_rank",
    "linalg_norm_np", "linalg_extractdiag", "linalg_makediag", "linalg_syevd",
    "linalg_maketrian", "linalg_extracttrian",
    # spectral / sketching
    "fft", "ifft", "count_sketch",
    # probability densities
    "pdf_normal", "pdf_uniform", "pdf_gamma", "pdf_exponential",
    "pdf_poisson", "pdf_negative_binomial",
    "pdf_generalized_negative_binomial", "pdf_dirichlet",
    # optimizer update kernels (master weights are fp32)
    "sgd_update", "sgd_mom_update", "nag_mom_update", "adam_update",
    "adamw_update", "adagrad_update", "adadelta_update", "ftrl_update",
    "rmsprop_update", "rmspropalex_update", "signsgd_update",
    "signum_update", "lamb_update_phase1", "lamb_update_phase2",
    "multi_sgd_update", "multi_sgd_mom_update", "multi_lamb_update",
    "multi_lans_update",
    # np-surface additions (ops/np_extra.py): accumulating statistics,
    # exp/log-backed windows+distributions, and linalg stay fp32
    "std", "var", "average", "percentile", "square_sum", "einsum",
    "arctan2", "arctan2_scalar", "rarctan2_scalar", "copysign",
    "copysign_scalar", "rcopysign_scalar", "rpower_scalar",
    "rdiv_scalar", "interp", "polyval", "nan_to_num",
    "linalg_eig", "linalg_eigvals", "linalg_tensorsolve",
    "hanning", "hamming", "blackman", "logspace",
    "laplace", "gumbel", "logistic", "rayleigh", "pareto", "weibull",
    "powerd", "generalized_negative_binomial",
    "SoftmaxActivation",
]

WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_maximum",
    "broadcast_minimum", "broadcast_hypot", "add_n", "concat", "stack",
    "where", "elemwise_add", "elemwise_sub", "elemwise_mul",
    "elemwise_div", "amp_multicast",
    "fmax", "fmin", "fmod", "cross", "kron", "tensordot",
    "hstack", "vstack", "dstack", "column_stack",
]

# Everything else: dtype-neutral — runs in whichever precision arrives.
# Kept explicit so the classification is EXHAUSTIVE over the registry
# (tests fail when a new op lands unclassified, mirroring the reference's
# all-ops list files).
FP16_FP32_FUNCS = [
    # activations / simple elementwise
    "Activation", "LeakyReLU", "relu", "sigmoid", "tanh", "softsign",
    "hard_sigmoid", "abs", "sign", "negative", "ceil", "floor", "rint",
    "fix", "trunc", "clip", "sin", "cos", "tan", "sinh", "cosh",
    "maximum_scalar", "minimum_scalar", "add_scalar", "sub_scalar",
    "mul_scalar", "div_scalar", "mod_scalar",
    # comparisons / logic (dtype-insensitive outputs)
    "equal_scalar", "not_equal_scalar", "greater_scalar",
    "greater_equal_scalar", "lesser_scalar", "lesser_equal_scalar",
    "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
    "broadcast_greater_equal", "broadcast_lesser",
    "broadcast_lesser_equal", "broadcast_logical_and",
    "broadcast_logical_or", "broadcast_logical_xor", "logical_not",
    "logical_and", "logical_or", "logical_xor", "logical_and_scalar",
    "logical_or_scalar", "logical_xor_scalar", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not", "isnan", "isinf",
    "isfinite", "allclose", "all_finite", "multi_all_finite",
    # shape/index/move ops
    "reshape", "Reshape", "npx_reshape", "flatten", "transpose", "expand_dims",
    "squeeze", "swapaxes", "SwapAxis", "slice", "slice_axis",
    "slice_like", "split", "SliceChannel", "take", "batch_take",
    "embedding", "one_hot", "pick", "gather_nd", "scatter_nd",
    "index_copy", "index_array", "boolean_mask", "broadcast_axis",
    "broadcast_to", "repeat", "tile", "reverse", "roll", "rot90", "pad",
    "Pad", "depth_to_space", "space_to_depth", "diag", "triu", "tril",
    "trace", "Crop", "sequence_mask", "sequence_last", "sequence_reverse",
    "sldwin_atten_mask_like", "choose_element_0index",
    "fill_element_0index", "unravel_index", "ravel_multi_index",
    "shape_array", "size_array", "cast", "Cast", "_copy", "_index",
    "BlockGrad", "arange_like",
    # ordering / extrema (value-preserving)
    "argmax", "argmin", "argmax_channel", "argsort", "sort", "topk",
    "max", "min", "unique",
    # pooling / resampling (window moves, no accumulation hazard in bf16)
    "Pooling", "AdaptiveAvgPooling2D", "UpSampling", "BilinearResize2D",
    "BilinearSampler", "GridGenerator", "SpatialTransformer", "ROIAlign",
    "PSROIPooling", "Dropout",
    # detection (mask/compare logic)
    "box_iou", "box_nms", "box_encode", "box_decode",
    "bipartite_matching", "multibox_prior", "multibox_target",
    "multibox_detection", "Proposal", "mrcnn_mask_target",
    # creation / random (dtype comes from attrs)
    "zeros", "ones", "full", "eye", "arange", "linspace", "zeros_like",
    "ones_like", "normal", "uniform", "randint", "randn", "bernoulli",
    "exponential", "poisson", "negative_binomial", "random_gamma",
    "multinomial", "shuffle",
    # int8 quantization domain (outside amp entirely)
    "quantize", "dequantize", "requantize", "quantized_conv",
    "quantized_fully_connected", "quantize_v2", "quantized_act",
    "quantized_pooling", "quantized_flatten", "quantized_concat",
    "quantized_elemwise_add", "quantized_elemwise_mul",
    "quantized_batch_norm", "quantized_embedding", "calibrate_entropy",
    "intgemm_maxabsolute", "intgemm_prepare_data",
    "intgemm_prepare_weight", "intgemm_take_weight",
    "intgemm_fully_connected",
    # optimizer updates (run in the dtype of their state; mp_* variants
    # own the fp32 master-weight logic internally)
    "ftml_update", "group_adagrad_update", "multi_lars",
    "mp_sgd_update", "mp_sgd_mom_update", "mp_nag_mom_update",
    "mp_lamb_update_phase1", "mp_lamb_update_phase2",
    "multi_mp_sgd_update", "multi_mp_sgd_mom_update",
    "preloaded_multi_sgd_update", "preloaded_multi_sgd_mom_update",
    "preloaded_multi_mp_sgd_update", "preloaded_multi_mp_sgd_mom_update",
    # bookkeeping / data movement (dtype-preserving)
    "amp_cast", "broadcast_like", "reshape_like", "cast_storage",
    "split_v2", "slice_assign", "slice_assign_scalar", "scatter_set_nd",
    "reset_arrays", "histogram", "getnnz", "dynamic_reshape",
    "identity_with_attr_like_rhs", "IdentityAttachKLSparseReg",
    "im2col", "col2im", "ROIPooling", "Custom",
    # device image ops (preprocessing domain)
    "to_tensor", "image_normalize", "image_resize", "image_crop",
    "image_random_crop", "image_random_resized_crop",
    # rroi / graph / sparse
    "RROIAlign", "edge_id", "sparse_retain",
    # adamw/lamb/lans mp+multi variants (fp32 master logic internal)
    "mp_adamw_update", "multi_adamw_update", "multi_mp_adamw_update",
    "multi_mp_lamb_update", "multi_mp_lans_update",
    # np-surface additions (ops/np_extra.py): dtype-preserving
    # manipulation, indexing, integer/bool ops, STE quantization helpers
    "all", "any", "around", "round", "bincount", "diff", "ediff1d",
    "nonzero", "hsplit", "dsplit", "moveaxis", "rollaxis", "diagonal",
    "diagflat", "diag_indices_from", "fill_diagonal", "delete", "insert",
    "atleast_1d", "atleast_2d", "atleast_3d", "share_memory",
    "full_like", "indices", "tri", "tril_indices",
    "lcm", "lcm_scalar", "ldexp_scalar", "rldexp_scalar",
    "fmax_scalar", "fmin_scalar", "fmod_scalar", "rfmod_scalar",
    "rsub_scalar", "rmod_scalar",
    "bitwise_and_scalar", "bitwise_or_scalar", "bitwise_xor_scalar",
    "where_lscalar", "where_rscalar", "where_scalar2",
    "advanced_indexing", "advanced_indexing_multiple",
    "boolean_mask_assign_scalar", "boolean_mask_assign_tensor",
    "index_add", "index_update", "constraint_check", "choice",
    "round_ste", "sign_ste", "gradientmultiplier",
    # dgl graph sampling (host-side minibatch construction)
    "dgl_csr_neighbor_uniform_sample",
    "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
    "dgl_adjacency", "dgl_graph_compact",
]
