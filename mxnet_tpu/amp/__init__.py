"""``mx.amp`` — automatic mixed precision.

Reference analog: ``python/mxnet/contrib/amp/amp.py:281-454`` (op-list
driven fp16 casting with dynamic loss scaling).  TPU-native defaults to
**bfloat16**: the MXU computes bf16 matmuls natively and bf16 shares
fp32's exponent range, so loss scaling is unnecessary (still provided for
fp16 parity).  ``init()`` installs a per-op cast policy at the operator
dispatch layer — the imperative analog of the reference's symbolic
``amp_cast`` insertion pass (src/nnvm/low_precision_pass.cc); under
hybridize the casts trace into the XLA graph and fuse away.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "uninit", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "LossScaler", "lists"]

_LOW = frozenset(lists.LOW_PRECISION_FUNCS)
_F32 = frozenset(lists.FP32_FUNCS)
_WIDEST = frozenset(lists.WIDEST_TYPE_CASTS)


class _AmpState:
    """Process-wide AMP state (the dispatch hook is global, so the policy
    must be too — training loops often run on worker threads)."""

    def __init__(self):
        self.target_dtype = None
        self.loss_scaler: Optional[LossScaler] = None


_STATE = _AmpState()


_FUSED_CONV_BN = frozenset(("_fused_conv1x1_bn", "_fused_convkxk_bn",
                            "_fused_conv1x1_bn_act"))


def _policy(op_name, arrays):
    """Cast op inputs per the op lists (invoked from ndarray dispatch)."""
    target = _STATE.target_dtype
    if target is None:
        return arrays
    if op_name in _FUSED_CONV_BN:
        # dedicated rule: the conv operands (x, w, optional bias) follow
        # the Convolution LOW cast, but the trailing gamma/beta are
        # BatchNorm parameters and must stay fp32 EXACTLY like the
        # unfused path (BatchNorm sits in FP32_FUNCS) — downcasting them
        # would round the affine and the running statistics inference
        # consumes.  The kernel accumulates fp32 internally either way.
        head = [a.astype(target)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in arrays[:-2]]
        return head + list(arrays[-2:])
    if op_name in _LOW:
        return [a.astype(target)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in arrays]
    if op_name in _F32:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype == target else a
                for a in arrays]
    if op_name in _WIDEST:
        dtypes = {a.dtype for a in arrays if hasattr(a, "dtype")}
        if jnp.float32 in dtypes and target in dtypes:
            return [a.astype(jnp.float32)
                    if hasattr(a, "dtype") and a.dtype == target else a
                    for a in arrays]
    return arrays


def init(target_dtype="bfloat16"):
    """Enable AMP globally (reference amp.init).  bfloat16 (default) or
    float16.  Bumps the AMP generation so hybridized graphs retrace under
    the new cast policy."""
    if target_dtype in ("bfloat16", jnp.bfloat16):
        _STATE.target_dtype = jnp.bfloat16
        _STATE.loss_scaler = None  # bf16 needs no scaling
    elif target_dtype in ("float16", onp.float16):
        _STATE.target_dtype = jnp.float16
        _STATE.loss_scaler = LossScaler()  # fresh scale per session
    else:
        raise ValueError("target_dtype must be bfloat16 or float16")
    from ..ndarray import ndarray as _ndmod

    _ndmod._amp_policy = _policy
    _ndmod._amp_generation += 1


def uninit():
    _STATE.target_dtype = None
    _STATE.loss_scaler = None
    from ..ndarray import ndarray as _ndmod

    _ndmod._amp_policy = None
    _ndmod._amp_generation += 1


def init_trainer(trainer):
    """Attach the loss scaler to a Trainer (reference amp.init_trainer)."""
    cfg = getattr(trainer, "_kvstore_params", {})
    if getattr(trainer, "_update_on_kvstore", None) or \
            cfg.get("update_on_kvstore"):
        raise MXNetError(
            "AMP does not support update_on_kvstore=True: overflowed "
            "updates applied server-side cannot be skipped — create the "
            "Trainer with update_on_kvstore=False")
    # lazily-resolved kvstore placement is re-checked in Trainer.step
    # (scaler present + _update_on_kvstore -> MXNetError before allreduce)
    if _STATE.target_dtype == jnp.float16 and _STATE.loss_scaler is None:
        _STATE.loss_scaler = LossScaler()
    trainer._amp_loss_scaler = _STATE.loss_scaler
    trainer._amp_original_scale = getattr(trainer, "_scale", 1.0)


class _ScaleLossCtx:
    def __init__(self, loss, trainer):
        self._loss = loss
        self._trainer = trainer

    def __enter__(self):
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        scale = scaler.loss_scale if scaler is not None else 1.0
        if hasattr(self._trainer, "_scale"):
            # always re-derive from the saved base so the division tracks
            # the CURRENT scale (including scale == 1.0 after decay)
            base = getattr(self._trainer, "_amp_original_scale",
                           self._trainer._scale)
            self._trainer._amp_original_scale = base
            self._trainer._scale = base / scale
        if isinstance(self._loss, (list, tuple)):
            return [l * scale for l in self._loss] if scale != 1.0 \
                else list(self._loss)
        return self._loss * scale if scale != 1.0 else self._loss

    def __exit__(self, *exc):
        return False


def scale_loss(loss, trainer):
    """Context manager scaling the loss and arranging grad unscale through
    Trainer rescale (reference amp.scale_loss)."""
    return _ScaleLossCtx(loss, trainer)


def unscale(trainer):
    """Explicitly divide gradients by the current scale (e.g. before manual
    gradient clipping) and reset the Trainer rescale so the step does not
    divide again (reference amp.unscale)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            if g is not None:
                g._set_data(g._data * inv)
    trainer._scale = getattr(trainer, "_amp_original_scale", trainer._scale)


_F32_LAYERS = ("BatchNorm", "SyncBatchNorm", "LayerNorm", "GroupNorm",
               "InstanceNorm")


def convert_hybrid_block(net, target_dtype="bfloat16", ctx=None):
    """Cast a Block for low-precision inference/training (reference
    amp.convert_hybrid_block).  Parameters cast to ``target_dtype`` except
    those owned by normalization layers, which stay fp32 (the op policy
    casts their inputs up at dispatch).  ``ctx`` additionally re-homes the
    parameters, matching the reference signature."""

    def walk(block):
        if type(block).__name__ in _F32_LAYERS:
            return
        for p in block._reg_params.values():
            if p._data is not None:
                p.cast(target_dtype)
            else:
                p.dtype = target_dtype
        for child in block._children.values():
            walk(child)

    walk(net)

    # The reference's converted symbol carries amp_cast nodes at its input
    # edges; the analog here is an input-casting forward bound on the
    # instance — hybridize traces it, so the casts land inside the compiled
    # graph exactly like the reference's graph rewrite.
    from ..ndarray.ndarray import NDArray

    jdt = jnp.bfloat16 if target_dtype in ("bfloat16", jnp.bfloat16) \
        else jnp.float16
    orig_forward = net.forward

    def _cast_in(a):
        if isinstance(a, NDArray) and jnp.issubdtype(a._data.dtype,
                                                     jnp.floating):
            return a.astype(jdt)
        return a

    def cast_forward(*args, **kwargs):
        return orig_forward(*[_cast_in(a) for a in args],
                            **{k: _cast_in(v) for k, v in kwargs.items()})

    net.forward = cast_forward
    if getattr(net, "_cached", None):
        net._cached = {}            # force a retrace under the new dtypes
    if ctx is not None:
        net.reset_ctx(ctx)
    return net
