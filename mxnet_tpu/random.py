"""Global PRNG state.

Reference analog: per-device mshadow/curand generators seeded by
``mx.random.seed`` (``src/common/random_generator.*``, ``MXRandomSeed``).
TPU-native design: a threefry key chain (counter-based, reproducible across
replicas/shards — what the survey recommends for TPU).  Eager random ops
split a fresh subkey per call; traced code (Dropout in a hybridized block)
receives keys as explicit inputs so graphs stay pure.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_key"]

_lock = threading.Lock()
_KEY = None  # lazily created: touching the backend at import time would
#              initialize devices before the user can configure platforms


class _TraceState(threading.local):
    def __init__(self):
        super().__init__()
        self.stack = []


_TRACE = _TraceState()


def seed(seed_state: int, ctx=None):
    """Seed the global generator (reference python/mxnet/random.py:30)."""
    global _KEY
    with _lock:
        _KEY = jax.random.PRNGKey(int(seed_state))
    # host-side sampling streams (graph minibatch construction) follow
    from .ops import graph_sampling

    graph_sampling.seed_rng(int(seed_state))


def push_trace_key(key):
    """Enter traced-RNG mode: while active, ``next_key`` splits from ``key``
    (a tracer) instead of the global concrete chain, so hybridized graphs
    stay pure and get fresh randomness per call via the key argument."""
    _TRACE.stack.append(key)


def pop_trace_key():
    _TRACE.stack.pop()


def in_trace() -> bool:
    return bool(_TRACE.stack)


def next_key():
    if _TRACE.stack:
        k, sub = jax.random.split(_TRACE.stack[-1])
        _TRACE.stack[-1] = k
        return sub
    global _KEY
    with _lock:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(0)
        _KEY, sub = jax.random.split(_KEY)
        return sub


def current_key():
    global _KEY
    with _lock:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(0)
    return _KEY
