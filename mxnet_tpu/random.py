"""Global PRNG state.

Reference analog: per-device mshadow/curand generators seeded by
``mx.random.seed`` (``src/common/random_generator.*``, ``MXRandomSeed``).
TPU-native design: a threefry key chain (counter-based, reproducible across
replicas/shards — what the survey recommends for TPU).  Eager random ops
split a fresh subkey per call; traced code (Dropout in a hybridized block)
receives keys as explicit inputs so graphs stay pure.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_key"]

_lock = threading.Lock()
_KEY = jax.random.PRNGKey(0)


def seed(seed_state: int, ctx=None):
    """Seed the global generator (reference python/mxnet/random.py:30)."""
    global _KEY
    with _lock:
        _KEY = jax.random.PRNGKey(int(seed_state))


def next_key():
    global _KEY
    with _lock:
        _KEY, sub = jax.random.split(_KEY)
        return sub


def current_key():
    return _KEY
