"""Fault-tolerant serving plane: a replica router with health-checked
failover, per-request deadlines, hedged retries, and circuit breakers.

``serving.py`` (PR 4) and ``serving_decode.py`` (PR 8) each serve
through ONE engine: a wedged or killed engine takes every in-flight and
queued request down with it.  This module is the layer a fleet of users
actually hits — ROADMAP item 3(d)'s router over co-hosted engine
replicas, built from the tail-at-scale playbook (Dean & Barroso, "The
Tail at Scale") on primitives PRs 2–13 already proved:

1. **Per-request deadlines, ONE budget** — ``infer(x,
   deadline_us=...)`` / ``generate(p, deadline_us=...)`` pin an
   absolute expiry at admission; the admission cost-table check, queue
   wait, every failover retry, every backoff, and every hedge draw
   from that single budget via :func:`faults.deadline_scope` threaded
   through :func:`faults.retry_call` — never multiplied per-site
   timeouts.  An exhausted budget is a typed
   ``ShedError(kind="deadline")``, never a hang.

2. **Health** — every replica carries (a) a liveness heartbeat on the
   in-memory :class:`~mxnet_tpu.parallel.elastic.HeartbeatMonitor`
   (the kvstore rank-liveness monitor generalized to engines; a beat
   is stamped per dispatch completion, so a replica with an
   outstanding dispatch and a stale beat is WEDGED, breaker-tripped,
   and failed over inside ``MXNET_ROUTER_WEDGE_S``), and (b) a
   :class:`CircuitBreaker` (closed → open → half-open,
   ``MXNET_ROUTER_BREAKER_*``): ``MXNET_ROUTER_BREAKER_ERRS``
   failures inside the rolling outcome window eject the replica
   BEFORE most clients feel it; after the cooldown one half-open
   probe request re-admits it (or re-opens on failure).

3. **Failover + hedging** — a dispatch lost to replica death,
   breaker-open, a wedge, or an engine-side overload shed re-dispatches
   transparently to a healthy replica under the ``router.dispatch``
   fault site (idempotent under greedy decode: the re-run is
   token-exact vs the ``eager_generate`` oracle — proven by
   tests/test_serving_router.py and the router drills).  With
   ``MXNET_ROUTER_HEDGE_PCTL`` set, a dispatch outstanding past the
   fleet's p<N> latency issues ONE hedged duplicate on a different
   replica with first-wins cancellation.

4. **Balancing on live telemetry** — replica choice scores the PR-10
   surfaces (engine queue depth, in-flight cost, KV page-pool
   headroom, router-side in-flight) and the breaker state, not
   round-robin.

5. **Degraded modes** — every breaker open: the router sheds
   ``ShedError(kind="unavailable")`` instead of hanging, or — with
   ``MXNET_ROUTER_EAGER_FALLBACK`` — serves single requests through
   the eager path.  A preemption notice sheds ``kind="draining"`` at
   the router edge, and ``engine.waitall()`` drains the router's
   in-flight dispatches like every other drainable.

6. **Elastic membership (ISSUE 17)** — the fleet changes shape under
   fire.  :meth:`ReplicaRouter.add_replica` /
   :meth:`~ReplicaRouter.drain_replica` move a replica through JOINING
   → SERVING → DRAINING → GONE: a joining replica warms
   (``engine.warmup()`` + the persistent program cache — 0 fresh
   compiles when ``MXNET_PROGRAM_CACHE_DIR`` is warm) BEFORE taking
   traffic; a draining replica finishes its in-flight rows, hands
   queued work back through token-exact failover (a per-replica
   ``draining`` shed fails over; only a process-wide preemption
   refuses), and detaches with a clean ``PagePool.audit()``.
   Membership mutations happen under one site
   (``faults.inject("router.scale")``) and never race
   dispatch/hedge/probe threads: indices are append-only, retired
   replicas stay as GONE tombstones, and ``_pick`` only ever sees
   SERVING.  Replicas may live in other processes/hosts
   (:class:`~mxnet_tpu.serving_remote.RemoteReplica`) — same breaker,
   wedge, deadline, and trace semantics over the wire.
   :class:`FleetSupervisor` closes the loop: an
   ``MXNET_ROUTER_AUTOSCALE`` thread prices scale-up/down from the
   same live telemetry ``_pick`` balances on (queue depth, page-pool
   headroom, fleet p99 — arXiv:2008.01040's measure-don't-guess) and
   executes scale-down as exactly a scheduled graceful preemption
   (SIGTERM → typed draining sheds → drain → exit 83), so autoscaling
   exercises, not bypasses, the PR-11 machinery.

The chaos matrix lives in ``mxnet_tpu/drills.py`` (``router`` child:
replica kill mid-decode, wedged-dispatch hang, breaker flap, deadline
storm, shared-prefix storm, scale storm, remote host loss) and is
gated by ``tools/check_availability_budget.py``: 0 dropped requests,
failover p99 inside a budget multiple of steady-state p99, 0 leaked
KV pages after a kill, breaker re-admission inside the probe budget,
join-to-first-served and kill-to-recovered inside declared walls.
``tools/check_dispatch_budget.py``'s ``router`` lane pins
zero-overhead-off: one replica, hedging off, breaker closed, no
supervisor — dispatch/retrace/host-sync counts identical to the bare
engine.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from . import config as _config
from . import faults as _faults
from . import preemption as _preemption
from . import telemetry as _telemetry
from .faults import ShedError
from .parallel.elastic import HeartbeatMonitor

__all__ = ["ReplicaRouter", "CircuitBreaker", "ReplicaUnavailable",
           "FleetSupervisor",
           "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN",
           "REPLICA_JOINING", "REPLICA_SERVING", "REPLICA_DRAINING",
           "REPLICA_GONE"]

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

# replica membership lifecycle (ISSUE 17).  Append-only indices:
# a retired replica stays in the list as a GONE tombstone so every
# in-flight ``req.failed`` set, breaker hook, and telemetry record
# keeps its index meaning forever.
REPLICA_JOINING = "joining"      # admitted to the fleet, still warming
REPLICA_SERVING = "serving"      # eligible for _pick / probe / hedge
REPLICA_DRAINING = "draining"    # no new dispatches; in-flight finishing
REPLICA_GONE = "gone"            # detached; tombstone only


class ReplicaUnavailable(_faults.TransientFault):
    """One replica failed a dispatch (death, wedge, overload shed) —
    retryable by the ``router.dispatch`` policy: the next attempt
    fails over to a different replica."""

    def __init__(self, *args, index: Optional[int] = None):
        super().__init__(*args)
        self.index = index


class _NoHealthyReplica(RuntimeError):
    """Every replica is excluded or breaker-open: NOT retryable —
    the router goes straight to its degraded mode."""


class CircuitBreaker:
    """Per-replica error-rate breaker: CLOSED (traffic flows; failures
    accumulate in a rolling outcome window) → OPEN (``errs`` failures
    in the window, a wedge, or a death trip it; no traffic) →
    HALF-OPEN (after ``cooldown_s``; exactly ONE probe request
    admitted) → CLOSED on probe success / back to OPEN on failure.

    ``clock`` is injectable so the state machine unit-tests without
    real waiting.  ``on_transition(old, new, reason)`` feeds the
    router's counters/events."""

    def __init__(self, errs: Optional[int] = None,
                 window: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        self.errs = int(_config.get("MXNET_ROUTER_BREAKER_ERRS")
                        if errs is None else errs)
        self.window = int(_config.get("MXNET_ROUTER_BREAKER_WINDOW")
                          if window is None else window)
        self.cooldown_s = float(
            _config.get("MXNET_ROUTER_BREAKER_COOLDOWN_S")
            if cooldown_s is None else cooldown_s)
        self._clock = clock
        self._on_transition = on_transition
        self._outcomes: "deque[bool]" = deque(maxlen=self.window)
        self._state = BREAKER_CLOSED
        self._opened_at: Optional[float] = None
        self._probe_out = False
        self._lock = threading.RLock()

    def state(self) -> str:
        """Current state; applies the lazy OPEN → HALF-OPEN cooldown
        transition."""
        with self._lock:
            if self._state == BREAKER_OPEN and \
                    self._clock() - self._opened_at >= self.cooldown_s:
                self._to(BREAKER_HALF_OPEN, "cooldown elapsed")
            return self._state

    def allow(self) -> bool:
        """May a dispatch go out now?  CLOSED: always.  HALF-OPEN: one
        probe at a time (the caller's dispatch IS the probe).  OPEN:
        never."""
        with self._lock:
            st = self.state()
            if st == BREAKER_CLOSED:
                return True
            if st == BREAKER_HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._probe_out = False
            if self._state == BREAKER_HALF_OPEN:
                self._outcomes.clear()
                self._to(BREAKER_CLOSED, "probe succeeded")
            elif self._state == BREAKER_CLOSED:
                self._outcomes.append(True)

    def record_failure(self, reason: str = "") -> None:
        with self._lock:
            self._probe_out = False
            if self._state == BREAKER_HALF_OPEN:
                self._to(BREAKER_OPEN, f"probe failed: {reason}")
                return
            self._outcomes.append(False)
            if self._state == BREAKER_CLOSED and \
                    sum(1 for ok in self._outcomes if not ok) >= self.errs:
                self._to(BREAKER_OPEN, reason or "error threshold")

    def trip(self, reason: str) -> None:
        """Immediate ejection (wedge / replica death): OPEN now, with a
        fresh cooldown."""
        with self._lock:
            self._probe_out = False
            if self._state != BREAKER_OPEN:
                self._to(BREAKER_OPEN, reason)
            else:
                self._opened_at = self._clock()

    def _to(self, new: str, reason: str) -> None:
        old, self._state = self._state, new
        if new == BREAKER_OPEN:
            self._opened_at = self._clock()
        if self._on_transition is not None:
            self._on_transition(old, new, reason)


class _Replica:
    __slots__ = ("index", "engine", "breaker", "key", "in_flight",
                 "state")

    def __init__(self, index: int, engine, breaker: CircuitBreaker,
                 key: str, state: str = REPLICA_SERVING):
        self.index = index
        self.engine = engine
        self.breaker = breaker
        self.key = key
        self.in_flight = 0
        self.state = state


class _Dispatch:
    """One engine call in flight on a router worker thread."""

    __slots__ = ("replica", "hedge", "t_start", "t_done", "done",
                 "result", "error", "abandoned", "released", "thread")

    def __init__(self, replica: _Replica, hedge: bool):
        self.replica = replica
        self.hedge = hedge
        self.t_start = time.monotonic()
        self.t_done = 0.0
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.abandoned = False
        self.released = False
        self.thread: Optional[threading.Thread] = None


class _RouterRequest:
    __slots__ = ("fn", "until", "label", "eager_fn", "prompt", "failed",
                 "cv", "hedged", "attempt", "t0", "trace_id")

    def __init__(self, fn, until: Optional[float], label: str,
                 eager_fn: Optional[Callable],
                 prompt: Optional[List[int]] = None):
        self.fn = fn                  # fn(engine) -> result
        self.until = until            # absolute monotonic expiry
        self.label = label
        self.eager_fn = eager_fn
        self.prompt = prompt          # token ids, for prefix affinity
        self.failed: Set[int] = set() # replica indices that failed it
        self.cv = threading.Condition()
        self.hedged = False
        self.attempt = 0
        self.t0 = time.monotonic()
        # the request's ONE identity, minted at admission and re-entered
        # by every dispatch/hedge thread it touches (ISSUE 15); None
        # with tracing disabled — zero trace fields anywhere
        self.trace_id: Optional[str] = None


def _weak_serving_count(router: "ReplicaRouter"):
    """Computed-gauge reader for the router's live SERVING count —
    weakly bound so the registry never pins a dead router (and a
    collected router reads 0, not a crash, at snapshot time)."""
    import weakref

    ref = weakref.ref(router)

    def read() -> float:
        r = ref()
        if r is None:
            return 0.0
        return float(sum(1 for rep in r._replicas
                         if rep.state == REPLICA_SERVING))
    return read


def _api_kind(engine) -> str:
    if hasattr(engine, "generate"):
        return "generate"
    if hasattr(engine, "infer"):
        return "infer"
    raise TypeError(f"replica {type(engine).__name__} exposes neither "
                    "infer() nor generate()")


class ReplicaRouter:
    """One ``infer()``/``generate()`` front over N engine replicas
    (all :class:`~mxnet_tpu.serving.ServingEngine`, all
    :class:`~mxnet_tpu.serving_decode.GenerativeEngine`, or
    :class:`~mxnet_tpu.serving_remote.RemoteReplica` shims over
    either); see the module docstring for the design.  Thread-safe and
    blocking, like the engines it fronts.

    ``replicas`` may hold the engines directly.  Every knob has a
    constructor override (tests/drills) and an ``MXNET_ROUTER_*``
    default (deploy).  Membership is dynamic: :meth:`add_replica` /
    :meth:`drain_replica` (and :class:`FleetSupervisor` driving them
    from telemetry)."""

    def __init__(self, replicas: Sequence, *, name: Optional[str] = None,
                 hedge_pctl: Optional[int] = None,
                 eager_fallback: Optional[bool] = None,
                 breaker_errs: Optional[int] = None,
                 breaker_window: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None,
                 wedge_s: Optional[float] = None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        kinds = {_api_kind(eng) for eng in replicas}
        if len(kinds) != 1:
            raise ValueError(
                "all replicas must serve the same API (got a mix of "
                f"{sorted(kinds)})")
        self._kind = kinds.pop()
        self.name = name or _telemetry.instance_name("router")
        self._hedge_pctl = int(_config.get("MXNET_ROUTER_HEDGE_PCTL")
                               if hedge_pctl is None else hedge_pctl)
        self._eager_fallback = bool(
            _config.get("MXNET_ROUTER_EAGER_FALLBACK")
            if eager_fallback is None else eager_fallback)
        self._wedge_s = float(_config.get("MXNET_ROUTER_WEDGE_S")
                              if wedge_s is None else wedge_s)
        # engine heartbeats: the kvstore HeartbeatMonitor generalized —
        # in-memory, string-keyed, stamped per dispatch completion
        self._hb = HeartbeatMonitor(timeout=self._wedge_s)
        self._stats = _telemetry.CounterGroup(
            _telemetry.instance_name("serving.router"),
            ("requests", "delivered", "dispatches", "failovers",
             "hedges", "hedge_wins", "hedge_cancelled", "sheds",
             "shed_unavailable", "shed_deadline", "shed_draining",
             "breaker_opens", "breaker_half_opens", "breaker_closes",
             "probes", "probe_failures", "wedged", "eager_fallbacks"),
            doc=f"ReplicaRouter counters (router {self.name!r})",
            family="serving.router")
        # fleet-lifecycle counters (ISSUE 17): membership and scaling
        # events, one family the perf gate holds tolerances on
        self._fleet = _telemetry.CounterGroup(
            _telemetry.instance_name("router.fleet"),
            ("joins", "drains", "gone", "warm_programs", "scale_ups",
             "scale_downs", "ticks", "scale_errors"),
            doc=f"Elastic fleet lifecycle counters (router "
                f"{self.name!r})",
            family="router.fleet")
        _telemetry.gauge_fn(
            f"{self._fleet.prefix}.serving_replicas",
            _weak_serving_count(self),
            doc="Live SERVING replica count of this router (computed "
                "at snapshot; 0 after the router is garbage-collected)",
            family="router.fleet")
        # breaker overrides are remembered so a replica joining later
        # (add_replica / the autoscaler) gets the same configuration
        # the founding replicas did
        self._breaker_kw = dict(errs=breaker_errs, window=breaker_window,
                                cooldown_s=breaker_cooldown_s)
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        for eng in replicas:
            self._admit_replica(eng, state=REPLICA_SERVING)
        # fleet dispatch latencies (successes only): the hedge
        # threshold's distribution + stats percentiles
        self._lat_dispatch: "deque[float]" = deque(maxlen=4096)
        self._lat_request: "deque[float]" = deque(maxlen=8192)
        self._inflight = 0
        self._closed = False
        from . import engine as _engine

        _engine.register_drainable(self)

    # -- public -------------------------------------------------------------
    def infer(self, *args, deadline_us: Optional[int] = None):
        """Route one one-shot inference request; blocks until a healthy
        replica delivers (failing over transparently), the deadline
        budget expires (``ShedError(kind="deadline")``), or every
        replica is ejected (``ShedError(kind="unavailable")`` /
        the eager fallback)."""
        if self._kind != "infer":
            raise RuntimeError(
                "this router fronts GenerativeEngine replicas — call "
                "generate()")
        first = self._replicas[0].engine
        return self._submit(
            lambda eng: eng.infer(*args), deadline_us, "infer",
            eager_fn=lambda: first._eager_forward(args))

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos: Optional[int] = None,
                 deadline_us: Optional[int] = None,
                 sampling=None) -> List[int]:
        """Route one generation request; failover re-runs the FULL
        request from the original prompt on the new replica — greedy
        decode makes the re-run token-exact, so a client never sees a
        replica death, only (bounded) extra latency.  ``sampling`` (a
        :class:`serving_decode.SamplingSpec`) rides the request to
        every replica it touches — the position-keyed counter PRNG
        makes a failed-over or hedged SAMPLED request replay
        token-exact too, same-seed-same-tokens on any same-config
        replica (the eager fallback runs the identical sampler)."""
        if self._kind != "generate":
            raise RuntimeError(
                "this router fronts ServingEngine replicas — call "
                "infer()")
        first = self._replicas[0].engine

        def eager():
            from .serving_decode import eager_generate

            return eager_generate(first._model, first._params,
                                  prompt, max_new_tokens, eos,
                                  sampling=sampling)

        return self._submit(
            lambda eng: eng.generate(prompt,
                                     max_new_tokens=max_new_tokens,
                                     eos=eos, sampling=sampling),
            deadline_us, "generate", eager_fn=eager,
            prompt=[int(t) for t in prompt])

    def stats(self) -> Dict[str, Any]:
        """Router counters, per-replica health, and request-latency
        percentiles."""
        out: Dict[str, Any] = dict(self._stats)
        out["fleet"] = self.fleet_stats()
        out["replicas"] = [{
            "index": r.index,
            "state": r.state,
            "breaker": r.breaker.state(),
            "in_flight": r.in_flight,
            "beat_age_s": self._hb.age(r.key),
        } for r in list(self._replicas)]
        lat = sorted(self._lat_request)
        if lat:
            out["p50_us"] = lat[len(lat) // 2] * 1e6
            out["p99_us"] = lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))] * 1e6
        else:
            out["p50_us"] = out["p99_us"] = 0.0
        out["hedge_threshold_s"] = self._hedge_threshold()
        return out

    def breaker_state(self, index: int) -> str:
        return self._replicas[index].breaker.state()

    def replica_state(self, index: int) -> str:
        return self._replicas[index].state

    def serving_replicas(self) -> int:
        """Live SERVING count (the autoscaler's fleet-size input and
        the ``router.fleet*.serving_replicas`` computed gauge)."""
        return sum(1 for r in list(self._replicas)
                   if r.state == REPLICA_SERVING)

    def fleet_stats(self) -> Dict[str, Any]:
        """Fleet-lifecycle counters + the per-state membership census."""
        out: Dict[str, Any] = dict(self._fleet)
        states = [r.state for r in list(self._replicas)]
        out["replica_count"] = len(states)
        for st in (REPLICA_JOINING, REPLICA_SERVING, REPLICA_DRAINING,
                   REPLICA_GONE):
            out[st] = states.count(st)
        return out

    # -- elastic membership (ISSUE 17) ---------------------------------------
    def _admit_replica(self, eng, state: str) -> _Replica:
        i = len(self._replicas)
        breaker = CircuitBreaker(on_transition=self._breaker_hook(i),
                                 **self._breaker_kw)
        rep = _Replica(i, eng, breaker, f"{self.name}.replica{i}",
                       state=state)
        self._hb.beat(rep.key)          # born live
        self._replicas.append(rep)
        return rep

    def add_replica(self, engine, *, warm: bool = True,
                    warmup_kwargs: Optional[Dict[str, Any]] = None
                    ) -> int:
        """Join ``engine`` to the fleet: JOINING (no traffic) → warm
        via ``engine.warmup()`` + the persistent program cache (0
        fresh compiles when ``MXNET_PROGRAM_CACHE_DIR`` is warm) →
        SERVING.  The append happens under the membership lock with a
        stable new index; dispatch/hedge/probe threads never see the
        replica until its state flips to SERVING, so a join can never
        race traffic onto a cold engine.  Returns the new index.

        A failed warmup tombstones the replica (GONE) and re-raises —
        the fleet is unchanged except for the tombstone."""
        if self._closed:
            raise RuntimeError("ReplicaRouter is closed")
        kind = _api_kind(engine)
        if kind != self._kind:
            raise ValueError(
                f"replica serves {kind}() but this router fronts "
                f"{self._kind}() replicas")
        # membership changes share one fault site with the supervisor:
        # an injected fault here = a scale-up that never happened
        _faults.inject("router.scale")
        with self._lock:
            rep = self._admit_replica(engine, state=REPLICA_JOINING)
        self._fleet.inc("joins")
        _telemetry.event("replica_join", self.name, replica=rep.index,
                         state=REPLICA_JOINING)
        t0 = time.monotonic()
        warmed = 0
        if warm and hasattr(engine, "warmup"):
            try:
                warmed = int(engine.warmup(**(warmup_kwargs or {})) or 0)
            except BaseException as e:
                rep.state = REPLICA_GONE
                self._fleet.inc("gone")
                _telemetry.event("replica_gone", self.name,
                                 replica=rep.index,
                                 reason=f"warmup failed: {e!r}")
                _faults.record_event("router.scale", "join_failed", e,
                                     router=self.name,
                                     replica=rep.index)
                raise
        self._fleet.inc("warm_programs", warmed)
        self._hb.beat(rep.key)
        rep.state = REPLICA_SERVING
        _telemetry.event("replica_join", self.name, replica=rep.index,
                         state=REPLICA_SERVING, warmed_programs=warmed,
                         warm_s=round(time.monotonic() - t0, 3))
        _faults.record_event("router.scale", "join", router=self.name,
                             replica=rep.index)
        return rep.index

    def drain_replica(self, index: int, timeout: float = 60.0) -> bool:
        """Gracefully retire replica ``index``: DRAINING (``_pick``
        stops sending traffic), queued work hands back — the engine's
        ``begin_drain()`` hook sheds its not-yet-live queue typed
        ``draining``, and each blocked dispatch fails over token-exact
        to a SERVING replica — in-flight rows finish, the KV pool is
        audited, and the replica tombstones GONE.

        Idempotent: draining a GONE replica returns True immediately; a
        concurrent drain of the same replica waits for the owner to
        finish.  Returns True when the replica detached clean (drained
        inside ``timeout`` with a clean audit)."""
        rep = self._replicas[index]
        if rep.state == REPLICA_GONE:
            return True
        _faults.inject("router.scale")
        with self._lock:
            if rep.state == REPLICA_GONE:
                return True
            owner = rep.state != REPLICA_DRAINING
            if owner:
                rep.state = REPLICA_DRAINING
        if not owner:
            # another thread owns this drain: wait it out (idempotent
            # double-drain, not a second lifecycle)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if rep.state == REPLICA_GONE:
                    return True
                time.sleep(0.002)
            return rep.state == REPLICA_GONE
        self._fleet.inc("drains")
        _telemetry.event("replica_drain", self.name, replica=index,
                         in_flight=rep.in_flight)
        _faults.record_event("router.scale", "drain", router=self.name,
                             replica=index)
        # handback: shed the engine's queued-but-not-live work typed
        # 'draining' so the blocked router dispatches re-route NOW
        # instead of waiting behind rows that will finish first
        if hasattr(rep.engine, "begin_drain"):
            try:
                rep.engine.begin_drain()
            except BaseException as e:
                _faults.record_event("router.scale", "handback_failed",
                                     e, router=self.name, replica=index)
        deadline = time.monotonic() + timeout
        drained = False
        while time.monotonic() < deadline:
            if rep.in_flight == 0:
                drained = True
                break
            time.sleep(0.002)
        audit = self._audit_replica(rep.engine)
        rep.state = REPLICA_GONE
        self._fleet.inc("gone")
        _telemetry.event("replica_gone", self.name, replica=index,
                         drained=drained, audit_clean=not audit,
                         audit=audit[:4])
        _faults.record_event("router.scale", "gone", router=self.name,
                             replica=index, drained=drained,
                             audit_clean=not audit)
        return drained and not audit

    @staticmethod
    def _audit_replica(engine) -> List[str]:
        """Detach-time page accounting: every page free/cached/
        referenced exactly once (local engines via ``pool_audit()``,
        remote replicas over the wire).  Engines with no KV pool audit
        clean by construction."""
        try:
            if hasattr(engine, "pool_audit"):
                return list(engine.pool_audit())
            if hasattr(engine, "pool"):
                return list((engine.pool() or {}).get("audit") or [])
        except BaseException as e:
            return [f"audit unavailable: {e!r}"]
        return []

    def probe(self, index: Optional[int] = None) -> Dict[int, bool]:
        """Actively probe open/half-open replicas with a zero-cost
        liveness call (``engine.load()``): a responsive replica's
        half-open breaker stays eligible for its one real probe
        request; a dead one trips.  Traffic-driven probing (the
        half-open dispatch) is the primary re-admission path — this is
        the explicit hook for idle fleets and drills."""
        out: Dict[int, bool] = {}
        targets = (list(self._replicas) if index is None
                   else [self._replicas[index]])
        for r in targets:
            if r.state != REPLICA_SERVING:
                continue                 # joining/draining/gone: no probe
            if r.breaker.state() == BREAKER_CLOSED:
                continue
            self._stats.inc("probes")
            try:
                if hasattr(r.engine, "load"):
                    r.engine.load()
                ok = not getattr(r.engine, "_closed", False)
            except BaseException:
                ok = False
            if not ok:
                self._stats.inc("probe_failures")
                r.breaker.trip("liveness probe failed")
            out[r.index] = ok
        return out

    def drain(self, timeout: float = 60.0) -> None:
        """engine.waitall() hook: block until every non-abandoned
        router dispatch completed (the engines drain themselves — they
        are registered drainables too)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    return
            time.sleep(0.002)

    def close(self) -> None:
        """Stop routing (the engines stay the caller's to close)."""
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- admission / submit -------------------------------------------------
    def _submit(self, fn, deadline_us: Optional[int], label: str,
                eager_fn: Optional[Callable],
                prompt: Optional[List[int]] = None):
        # the request's end-to-end trace identity is minted HERE (or
        # inherited from a caller's ambient scope) so the draining shed
        # below, every dispatch attempt, and the engine's own admission
        # all stamp one trace_id (ISSUE 15)
        with _telemetry.trace_scope() as ts:
            return self._submit_traced(fn, deadline_us, label, eager_fn,
                                       ts.trace_id, prompt)

    def _submit_traced(self, fn, deadline_us: Optional[int], label: str,
                       eager_fn: Optional[Callable],
                       trace_id: Optional[str],
                       prompt: Optional[List[int]] = None):
        if self._closed:
            raise RuntimeError("ReplicaRouter is closed")
        if _preemption.draining():
            self._shed("draining",
                       "router draining after a preemption notice; "
                       "re-queue on another host or after the restart")
        self._stats.inc("requests")
        if trace_id is not None:
            _telemetry.event("admit", self.name, label=label,
                             deadline_us=deadline_us)
        t0 = time.monotonic()
        # ONE budget: the tighter of the caller's ambient scope and the
        # per-request deadline_us, pinned absolute so every thread this
        # request touches draws from the same clock
        spans = []
        amb = _faults.deadline_remaining_us()
        if amb is not None:
            spans.append(amb / 1e6)
        if deadline_us is not None:
            spans.append(deadline_us / 1e6)
        until = (t0 + min(spans)) if spans else None
        req = _RouterRequest(fn, until, label, eager_fn, prompt)
        req.trace_id = trace_id
        try:
            result = _faults.retry_call(
                self._dispatch_attempt, req,
                site="router.dispatch",
                retries=max(1, 2 * len(self._replicas)),
                backoff=0.0,
                deadline_us=(int((until - t0) * 1e6)
                             if until is not None else None))
        except _faults.DeadlineExceeded as e:
            self._shed("deadline",
                       f"deadline budget exhausted after "
                       f"{(time.monotonic() - t0) * 1e6:.0f}us "
                       f"({req.attempt} dispatch attempt(s))", cause=e)
        except ShedError as e:
            if e.kind == "deadline":
                self._stats.inc("sheds")
                self._stats.inc("shed_deadline")
            raise
        except (ReplicaUnavailable, _NoHealthyReplica) as e:
            result = self._degraded(req, cause=e)
        t1 = time.monotonic()
        self._lat_request.append(t1 - t0)
        self._stats.inc("delivered")
        if trace_id is not None:
            _telemetry.event("retire", self.name, label=label,
                             attempts=req.attempt, hedged=req.hedged)
        _telemetry.record_span(
            "router.request", "serving", int(t0 * 1e9), int(t1 * 1e9),
            args={"router": self.name, "label": label,
                  "attempts": req.attempt, "hedged": req.hedged})
        return result

    def _shed(self, kind: str, reason: str,
              cause: Optional[BaseException] = None):
        self._stats.inc("sheds")
        self._stats.inc("shed_" + kind)
        _telemetry.event("shed", self.name, shed_kind=kind, reason=reason)
        _faults.record_event("router.dispatch", "shed", cause,
                             router=self.name, kind=kind, reason=reason)
        err = ShedError(f"[{self.name}] {reason}", kind=kind)
        if cause is not None:
            raise err from cause
        raise err

    # -- breaker / health -----------------------------------------------------
    def _breaker_hook(self, index: int):
        def hook(old: str, new: str, reason: str) -> None:
            key = {BREAKER_OPEN: "breaker_opens",
                   BREAKER_HALF_OPEN: "breaker_half_opens",
                   BREAKER_CLOSED: "breaker_closes"}[new]
            self._stats.inc(key)
            if old == BREAKER_HALF_OPEN and new == BREAKER_OPEN:
                self._stats.inc("probe_failures")
            _telemetry.event("breaker", self.name, replica=index,
                             state=new, prev=old, reason=reason)
            _faults.record_event("router.dispatch", "breaker",
                                 router=self.name, replica=index,
                                 state=new, prev=old, reason=reason)
        return hook

    def _pick(self, exclude: Set[int],
              prompt: Optional[List[int]] = None) -> Optional[_Replica]:
        """Healthiest replica by live telemetry: queue depth + in-flight
        cost + page-pool pressure (engine ``load()``) + router-side
        in-flight, minus prefix affinity (replicas whose KV pool
        already holds the prompt's hash chain score lower — shared
        prompts converge on the warm pages), breaker-closed replicas
        first, then ONE half-open probe.  Deterministic tie-break by
        replica index."""
        closed_scored = []
        half: List[_Replica] = []
        for r in list(self._replicas):
            if r.index in exclude:
                continue
            if r.state != REPLICA_SERVING:
                # JOINING warms first, DRAINING finishes what it has,
                # GONE is a tombstone — none take new traffic
                continue
            st = r.breaker.state()
            if st == BREAKER_CLOSED:
                closed_scored.append((self._score(r, prompt),
                                      r.index, r))
            elif st == BREAKER_HALF_OPEN:
                half.append(r)
        # a half-open replica is re-admitted BY PROBE: the next request
        # is the probe (one at a time), even while closed replicas
        # exist — otherwise a recovered replica starves half-open
        # forever behind its healthy neighbors
        for r in half:
            if r.breaker.allow():
                self._stats.inc("probes")
                return r
        if closed_scored:
            return min(closed_scored)[2]
        return None

    def _score(self, r: _Replica,
               prompt: Optional[List[int]] = None) -> float:
        try:
            load = r.engine.load() if hasattr(r.engine, "load") else {}
        except BaseException:
            # an unreachable replica (dead remote host) prices itself
            # to the back of the pick order — scoring never throws;
            # the dispatch that eventually hits it owns the blame
            # (breaker + failover)
            return float("inf")
        score = (float(r.in_flight)
                 + float(load.get("queue_depth", 0.0))
                 + float(load.get("in_flight", 0.0))
                 + float(load.get("pool_pressure", 0.0)))
        if prompt and hasattr(r.engine, "prefix_probe"):
            # each resident leading block is worth
            # MXNET_ROUTER_PREFIX_AFFINITY units of load: shared-prefix
            # traffic converges on the replica holding the warm pages
            # (prefix_probe is 0 with MXNET_PREFIX_CACHE off)
            weight = float(_config.get("MXNET_ROUTER_PREFIX_AFFINITY"))
            if weight > 0:
                score -= weight * r.engine.prefix_probe(prompt)
        return score

    def _hedge_threshold(self) -> Optional[float]:
        """p<MXNET_ROUTER_HEDGE_PCTL> of observed successful dispatch
        latencies (None while hedging is off or the distribution is
        too thin to trust)."""
        if not self._hedge_pctl:
            return None
        lat = sorted(self._lat_dispatch)
        if len(lat) < 16:
            return None
        return lat[min(len(lat) - 1,
                       int(len(lat) * self._hedge_pctl / 100))]

    # -- dispatch -------------------------------------------------------------
    def _dispatch_attempt(self, req: _RouterRequest):
        """One ``router.dispatch`` attempt: pick a replica, launch the
        engine call on a worker thread, and supervise it — completing,
        hedging past the latency threshold, declaring a wedge, or
        failing over.  Raising :class:`ReplicaUnavailable` hands
        control back to ``faults.retry_call``, whose next attempt IS
        the failover."""
        req.attempt += 1
        if req.attempt > 1:
            self._stats.inc("failovers")
        primary = self._pick(exclude=req.failed, prompt=req.prompt)
        if primary is None:
            raise _NoHealthyReplica(
                f"[{self.name}] no healthy replica "
                f"({len(req.failed)} failed this request; breakers: "
                f"{[r.breaker.state() for r in self._replicas]})")
        if req.attempt > 1:
            _telemetry.event("failover", self.name,
                             replica=primary.index,
                             failed=sorted(req.failed),
                             attempt=req.attempt, label=req.label)
        flights = [self._launch(primary, req, hedge=False)]
        last_err: Optional[BaseException] = None
        while flights:
            got = self._await_progress(req, flights)
            if got == "deadline":
                for f in flights:
                    self._abandon(f, "deadline")
                _faults.record_event(
                    "router.dispatch", "deadline",
                    router=self.name, label=req.label)
                raise _faults.DeadlineExceeded(
                    f"[{self.name}] request budget exhausted with "
                    f"{len(flights)} dispatch(es) in flight")
            if got == "hedge":
                req.hedged = True
                spare = self._pick(
                    exclude=req.failed
                    | {f.replica.index for f in flights},
                    prompt=req.prompt)
                if spare is not None:
                    self._stats.inc("hedges")
                    _telemetry.event(
                        "hedge", self.name, replica=spare.index,
                        primary=flights[0].replica.index,
                        threshold_s=self._hedge_threshold())
                    flights.append(self._launch(spare, req, hedge=True))
                continue
            d = got
            if not d.done.is_set():            # wedged, not completed
                self._stats.inc("wedged")
                _telemetry.event("breaker", self.name,
                                 replica=d.replica.index,
                                 state="wedged",
                                 outstanding_s=round(
                                     time.monotonic() - d.t_start, 3))
                d.replica.breaker.trip(
                    f"dispatch wedged > {self._wedge_s}s with no "
                    "heartbeat")
                self._abandon(d, "wedged")
                req.failed.add(d.replica.index)
                flights.remove(d)
                last_err = ReplicaUnavailable(
                    f"replica {d.replica.index} wedged",
                    index=d.replica.index)
                if not flights:
                    raise last_err
                continue
            flights.remove(d)
            if d.error is None:
                for f in flights:              # first-wins cancellation
                    self._abandon(f, "hedge lost")
                    self._stats.inc("hedge_cancelled")
                if d.hedge:
                    self._stats.inc("hedge_wins")
                d.replica.breaker.record_success()
                self._lat_dispatch.append(d.t_done - d.t_start)
                return d.result
            e = d.error
            if self._request_fault(e):
                # the REQUEST's own fault (bad arguments, its deadline
                # budget): no replica to blame, no failover
                for f in flights:
                    self._abandon(f, "request fault")
                raise e
            if isinstance(e, ShedError) and \
                    getattr(e, "kind", None) == "draining":
                # a deliberate drain (scale-down / remote preemption)
                # handing queued work back — the replica is leaving,
                # not sick: no breaker blame, just re-route
                _telemetry.event("handback", self.name,
                                 replica=d.replica.index,
                                 label=req.label)
            else:
                d.replica.breaker.record_failure(repr(e))
            req.failed.add(d.replica.index)
            last_err = e
            if not flights:
                raise ReplicaUnavailable(
                    f"replica {d.replica.index} failed {req.label}: "
                    f"{e!r}", index=d.replica.index) from e
        raise last_err or _NoHealthyReplica("no dispatch launched")

    def _request_fault(self, e: BaseException) -> bool:
        """Errors that belong to the request (or the whole process),
        not one replica: its deadline budget, a process-wide preemption
        drain (every co-hosted replica drains together — failover
        inside the process is futile; the client must re-queue
        elsewhere), or plainly bad arguments."""
        if isinstance(e, ShedError):
            if e.kind == "deadline":
                return True
            if e.kind == "draining":
                # only a PROCESS-WIDE preemption makes a draining shed
                # the request's problem.  One replica draining (a
                # scale-down, a remote replica's own preemption) hands
                # its queued work back: failover re-runs it
                # token-exact on a SERVING replica (ISSUE 17)
                return _preemption.draining()
            return False
        return isinstance(e, (ValueError, TypeError))

    def _launch(self, replica: _Replica, req: _RouterRequest,
                hedge: bool) -> _Dispatch:
        d = _Dispatch(replica, hedge)
        with self._lock:
            self._inflight += 1
            replica.in_flight += 1
        if req.trace_id is not None:
            # one record per dispatch attempt: replica id, attempt
            # index, and its hedge/failover marking — the trace's
            # "every attempt" contract (ISSUE 15)
            _telemetry.event("dispatch", self.name,
                             replica=replica.index, attempt=req.attempt,
                             hedge=hedge, failover=req.attempt > 1,
                             label=req.label)

        def run():
            try:
                # carry the request's ONE identity (and, below, its ONE
                # deadline budget) onto this worker thread — the engine
                # call's admission/shed/span records stamp the same
                # trace_id the router minted
                with _telemetry.trace_scope(trace_id=req.trace_id):
                    if req.until is not None:
                        with _faults.deadline_scope(
                                until=req.until, site="router.dispatch"):
                            d.result = req.fn(replica.engine)
                    else:
                        d.result = req.fn(replica.engine)
            except BaseException as e:
                d.error = e
            finally:
                d.t_done = time.monotonic()
                self._hb.beat(replica.key)     # heartbeat per dispatch
                self._release(d)
                d.done.set()
                with req.cv:
                    req.cv.notify_all()

        self._stats.inc("dispatches")
        t = threading.Thread(
            target=run, daemon=True,
            name=f"mxnet-router-{self.name}-r{replica.index}")
        d.thread = t
        t.start()
        return d

    def _release(self, d: _Dispatch) -> None:
        with self._lock:
            if not d.released:
                d.released = True
                self._inflight -= 1
                d.replica.in_flight -= 1

    def _abandon(self, d: _Dispatch, why: str) -> None:
        """Stop waiting on a dispatch (wedge, hedge loss, deadline):
        its thread finishes in the background, but it no longer counts
        toward drain() and its result is discarded."""
        if not d.abandoned:
            d.abandoned = True
            self._release(d)

    def _await_progress(self, req: _RouterRequest, flights: List[_Dispatch]):
        """Block until a flight completes, the hedge threshold passes,
        a flight wedges, or the deadline budget expires.  Returns the
        completed/wedged :class:`_Dispatch`, ``"hedge"``, or
        ``"deadline"``."""
        while True:
            now = time.monotonic()
            for d in flights:
                if d.done.is_set():
                    return d
            timers = []
            if req.until is not None:
                timers.append((req.until, "deadline"))
            if not req.hedged:
                thr = self._hedge_threshold()
                if thr is not None:
                    timers.append((flights[0].t_start + thr, "hedge"))
            for d in flights:
                # a replica beats per dispatch completion: while OTHER
                # dispatches complete on it, this one is slow, not
                # wedged — the wedge clock restarts at the newest beat
                age = self._hb.age(d.replica.key)
                idle = (now - d.t_start if age is None
                        else min(age, now - d.t_start))
                timers.append((now + self._wedge_s - idle, d))
            t, what = min(timers, key=lambda x: x[0])
            if t <= now:
                return what
            with req.cv:
                for d in flights:
                    if d.done.is_set():
                        return d
                req.cv.wait(timeout=min(t - now, 0.25))

    # -- degraded modes -------------------------------------------------------
    def _degraded(self, req: _RouterRequest, cause: BaseException):
        """Every replica ejected: the last-resort eager path
        (``MXNET_ROUTER_EAGER_FALLBACK``) or a typed ``unavailable``
        shed — never a hang."""
        if self._eager_fallback and req.eager_fn is not None:
            self._stats.inc("eager_fallbacks")
            _telemetry.event("fallback", self.name,
                             reason="router eager fallback "
                                    "(every replica unhealthy)",
                             label=req.label)
            _faults.record_event("router.dispatch", "eager_fallback",
                                 cause, router=self.name)
            return req.eager_fn()
        self._shed("unavailable",
                   f"every replica unhealthy for {req.label} "
                   f"({cause!r})", cause=cause)


class FleetSupervisor:
    """The autoscaler: a supervisor loop that prices scale-up/down
    from the SAME live telemetry the router balances on — mean queued
    work per SERVING replica (engine ``load()``: queue depth +
    in-flight occupancy), worst page-pool pressure, and the router's
    request p99 — never static thresholds alone (arXiv:2008.01040).

    - **Scale-up**: ``spawn()`` (caller-supplied: a co-hosted engine,
      or a :class:`~mxnet_tpu.serving_remote.RemoteReplica` over a
      process the caller launched) joins via
      :meth:`ReplicaRouter.add_replica` — warmed before it serves.
    - **Scale-down**: exactly a scheduled graceful preemption.  The
      youngest SERVING replica drains (:meth:`~ReplicaRouter.
      drain_replica`: typed ``draining`` handback + clean audit), and
      a process-backed replica is then told to ``preempt()`` — SIGTERM
      → ``engine.waitall()`` → exit ``MXNET_PREEMPTION_EXIT_CODE``
      (83); the PR-11 machinery IS the retirement path.
    - **Stability**: min/max bounds, one scaling action per
      ``cooldown_s`` (injectable ``clock`` so the state machine
      unit-tests without waiting), and a decision loop that never
      raises (errors land in ``router.fleet*.scale_errors`` + the
      ``router.scale`` fault-site event stream).

    ``start()`` is a no-op unless ``MXNET_ROUTER_AUTOSCALE`` (or the
    ``enabled=True`` override) — the zero-overhead-off contract: a
    disabled supervisor adds no thread, no timer, no dispatch."""

    def __init__(self, router: ReplicaRouter, spawn: Callable[[], Any],
                 *, retire: Optional[Callable[[Any, int], None]] = None,
                 enabled: Optional[bool] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 up_queue: Optional[float] = None,
                 down_queue: Optional[float] = None,
                 pool_high: Optional[float] = None,
                 warmup_kwargs: Optional[Dict[str, Any]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self._spawn = spawn
        self._retire = retire
        self._enabled = bool(_config.get("MXNET_ROUTER_AUTOSCALE")
                             if enabled is None else enabled)
        self._min = int(_config.get("MXNET_ROUTER_MIN_REPLICAS")
                        if min_replicas is None else min_replicas)
        self._max = int(_config.get("MXNET_ROUTER_MAX_REPLICAS")
                        if max_replicas is None else max_replicas)
        if not (1 <= self._min <= self._max):
            raise ValueError(
                f"need 1 <= min_replicas ({self._min}) <= max_replicas "
                f"({self._max})")
        self._cooldown_s = float(
            _config.get("MXNET_ROUTER_SCALE_COOLDOWN_S")
            if cooldown_s is None else cooldown_s)
        self._interval_s = float(
            _config.get("MXNET_ROUTER_SCALE_INTERVAL_S")
            if interval_s is None else interval_s)
        self._up_queue = float(
            _config.get("MXNET_ROUTER_SCALE_UP_QUEUE")
            if up_queue is None else up_queue)
        self._down_queue = float(
            _config.get("MXNET_ROUTER_SCALE_DOWN_QUEUE")
            if down_queue is None else down_queue)
        self._pool_high = float(
            _config.get("MXNET_ROUTER_SCALE_POOL_HIGH")
            if pool_high is None else pool_high)
        self._warmup_kwargs = warmup_kwargs
        self._clock = clock
        self._last_scale: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._mid_tick = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        """Spawn the supervisor thread (no-op when autoscaling is off
        or it is already running)."""
        if not self._enabled or self._thread is not None:
            return self
        from . import engine as _engine

        _engine.register_drainable(self)
        t = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mxnet-fleet-supervisor-{self.router.name}")
        self._thread = t
        t.start()
        return self

    def drain(self, timeout: float = 60.0) -> None:
        """engine.waitall() hook: wait out any in-progress scaling
        action (a half-joined replica must finish warming or
        tombstone).  A PROCESS preemption additionally parks the loop
        for good — ``_loop`` checks ``preemption.draining()`` — but a
        routine ``waitall`` leaves the supervisor running."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._mid_tick:
                return
            time.sleep(0.002)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            if _preemption.draining():
                return                   # the process is leaving
            self._mid_tick = True
            try:
                self.tick()
            except BaseException as e:   # the loop never dies
                self.router._fleet.inc("scale_errors")
                _faults.record_event("router.scale", "tick_error", e,
                                     router=self.router.name)
            finally:
                self._mid_tick = False

    # -- the decision -------------------------------------------------------
    def signals(self) -> Dict[str, float]:
        """The measured inputs one decision prices: mean queued work
        per SERVING replica, worst page-pool pressure, fleet p99."""
        reps = [r for r in list(self.router._replicas)
                if r.state == REPLICA_SERVING]
        queue = pool = 0.0
        for r in reps:
            try:
                load = (r.engine.load()
                        if hasattr(r.engine, "load") else {})
            except BaseException:
                continue                   # a dead replica prices as 0
            queue += (float(load.get("queue_depth", 0.0))
                      + float(load.get("in_flight", 0.0)))
            pool = max(pool, float(load.get("pool_pressure", 0.0)))
        lat = sorted(self.router._lat_request)
        p99 = (lat[min(len(lat) - 1, int(len(lat) * 0.99))]
               if lat else 0.0)
        return {"serving": float(len(reps)),
                "queue_per_replica": queue / max(len(reps), 1),
                "pool_pressure": pool,
                "p99_s": p99}

    def decide(self, sig: Optional[Dict[str, float]] = None
               ) -> Optional[str]:
        """``"up"``, ``"down"``, or ``None`` — pure pricing, no
        execution, no cooldown (tick applies those): up when the fleet
        is saturated (queued work per replica past the knob, or KV
        pool pressure critical) and under max; down when it is idle
        and over min."""
        sig = self.signals() if sig is None else sig
        n = int(sig["serving"])
        if n < self._min:
            return "up"
        if (sig["queue_per_replica"] >= self._up_queue
                or sig["pool_pressure"] >= self._pool_high):
            return "up" if n < self._max else None
        if sig["queue_per_replica"] <= self._down_queue \
                and sig["pool_pressure"] < self._pool_high / 2 \
                and n > self._min:
            return "down"
        return None

    def tick(self) -> Optional[str]:
        """One supervisor step: read the signals, apply cooldown +
        bounds, execute at most one scaling action.  Returns the
        action taken (``"up"``/``"down"``) or ``None``.  Callable
        directly (tests, drills) — the loop thread only calls this."""
        self.router._fleet.inc("ticks")
        sig = self.signals()
        action = self.decide(sig)
        if action is None:
            return None
        now = self._clock()
        if self._last_scale is not None and \
                now - self._last_scale < self._cooldown_s \
                and int(sig["serving"]) >= self._min:
            return None                  # cooling down (min is urgent)
        if action == "up":
            self._scale_up(sig)
        else:
            self._scale_down(sig)
        self._last_scale = self._clock()
        return action

    def _scale_up(self, sig: Dict[str, float]) -> None:
        t0 = time.monotonic()
        engine = self._spawn()
        index = self.router.add_replica(
            engine, warmup_kwargs=self._warmup_kwargs)
        self.router._fleet.inc("scale_ups")
        _telemetry.event("scale_up", self.router.name, replica=index,
                         join_s=round(time.monotonic() - t0, 3),
                         **{k: round(v, 4) for k, v in sig.items()})

    def _scale_down(self, sig: Dict[str, float]) -> None:
        # retire the YOUNGEST serving replica: replica 0 (the founding
        # member, often the local engine) is the last to go
        victims = [r for r in list(self.router._replicas)
                   if r.state == REPLICA_SERVING]
        if len(victims) <= self._min:
            return
        victim = victims[-1]
        clean = self.router.drain_replica(victim.index)
        if self._retire is not None:
            self._retire(victim.engine, victim.index)
        elif hasattr(victim.engine, "preempt"):
            # a process-backed replica exits through the PR-11 drain:
            # SIGTERM → typed draining sheds → waitall → exit 83
            try:
                victim.engine.preempt()
            except BaseException as e:
                _faults.record_event("router.scale", "preempt_failed",
                                     e, router=self.router.name,
                                     replica=victim.index)
        self.router._fleet.inc("scale_downs")
        _telemetry.event("scale_down", self.router.name,
                         replica=victim.index, clean=clean,
                         **{k: round(v, 4) for k, v in sig.items()})
