"""Preemption survival: the SIGTERM "preemption notice" -> graceful drain.

On TPU pods preemption is ROUTINE, not exceptional: the scheduler sends
SIGTERM, waits a grace window, then SIGKILLs the host
(docs/ROBUSTNESS.md's opening premise; whole-program compilation makes a
mid-run kill all-or-nothing).  Everything PRs 2-10 built — fault plans,
`run_elastic`, the async engine's drainable registry, async
checkpointing, the persistent compile cache — exists so that a kill
costs seconds, not a job; this module is the piece that CATCHES the
notice and turns it into an orderly exit:

1. :func:`install` registers SIGTERM/SIGINT handlers.  On the first
   signal :func:`notice` flips the process-wide **draining** flag
   (readable anywhere via :func:`draining`; exported as the computed
   telemetry gauge ``preemption.draining``), emits a ``drain`` event
   stamped with the current train-step index, and arms a grace
   watchdog (``MXNET_PREEMPTION_GRACE_S``) that force-exits if the
   drain wedges — the scheduler's SIGKILL would anyway, but the
   watchdog exits with a known code.
2. The draining flag stops new work at every admission edge: the
   serving engines refuse new requests with a typed
   :class:`faults.ShedError` of kind ``draining`` (never a timeout),
   and the device prefetcher stops staging new batches.
3. :func:`drain` runs ``engine.waitall()`` — prefetch transfers,
   deferred AMP reads, device metric queues, async checkpoint
   writers, and serving/decode queues all flush — then the registered
   :func:`on_drain` hooks (``run_elastic`` registers a final BLOCKING
   ``CheckpointManager.save`` of the last completed step).  The drain
   duration lands in the ``preemption.drain_s`` telemetry counter and
   a completion ``drain`` event.
4. The process exits with the distinguished code
   ``MXNET_PREEMPTION_EXIT_CODE`` (default 83) by raising
   :class:`Preempted` (a ``SystemExit``) in the main thread — so
   ``finally`` blocks still run — a supervisor or drill seeing that
   code KNOWS the newest checkpoint is the exact pre-signal state and
   restart-and-replay loses zero steps.  A drain that *failed* exits
   ``1`` instead: never trust the distinguished code after a failed
   drain.  A second signal while draining skips straight to the exit.

The whole lifecycle is drillable without real signals where a fault
plan suffices: ``notice()`` is directly callable, the ``exit_fn``
install parameter lets in-process tests observe the exit instead of
dying, and the ``preemption.drain`` injection site fires at the start
of every drain (a planned fault there proves a failed drain degrades
the exit code).  `mxnet_tpu/drills.py` runs the real-signal
end-to-end scenarios as subprocesses.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from . import config as _config
from . import telemetry as _telemetry
from .log import get_logger

__all__ = ["Preempted", "install", "uninstall", "installed", "draining",
           "notice", "drain", "on_drain", "remove_drain_hook", "reset",
           "exit_code", "grace_s"]

_LOG = get_logger("mxnet_tpu.preemption")


class Preempted(SystemExit):
    """The distinguished exit of a SUCCESSFUL graceful drain: raised in
    the main thread after the final checkpoint landed, so an uncaught
    one exits the process with ``MXNET_PREEMPTION_EXIT_CODE`` while
    ``finally`` blocks still run.  ``.code`` carries the exit code."""


# -- counters ---------------------------------------------------------------
_NOTICES = _telemetry.counter(
    "preemption.notices",
    "preemption notices taken (SIGTERM/SIGINT caught by the installed "
    "handler, or notice() called directly)")
_DRAIN_S = _telemetry.counter(
    "preemption.drain_s",
    "seconds the most recent graceful drain took (waitall + final "
    "checkpoint hooks)", kind="time")

# -- process-wide state -----------------------------------------------------
_DRAINING = threading.Event()
_LOCK = threading.Lock()
_STATE: Dict[str, object] = {
    "installed": False,
    "prev_handlers": {},        # signum -> previous handler
    "grace_s": None,            # install-time override, else knob
    "exit_code": None,          # install-time override, else knob
    "exit_fn": None,            # install-time override, else raise
    "watchdog": None,           # armed threading.Timer
}
_DRAIN_HOOKS: List[Callable[[], None]] = []

_telemetry.gauge_fn(
    "preemption.draining", lambda: int(_DRAINING.is_set()),
    "1 while the process is draining after a preemption notice "
    "(admission edges shed, prefetch stops staging)")


def draining() -> bool:
    """True once a preemption notice was taken: admission edges must
    refuse new work (typed ``ShedError`` kind ``draining``) and staging
    loops should wind down.  One Event read — hot-path safe."""
    return _DRAINING.is_set()


def installed() -> bool:
    return bool(_STATE["installed"])


def grace_s() -> float:
    """Effective grace budget (install override, else the knob)."""
    g = _STATE["grace_s"]
    return float(_config.get("MXNET_PREEMPTION_GRACE_S")
                 if g is None else g)


def exit_code() -> int:
    """Effective distinguished exit code (install override, else the
    knob)."""
    c = _STATE["exit_code"]
    return int(_config.get("MXNET_PREEMPTION_EXIT_CODE")
               if c is None else c)


def install(grace_s: Optional[float] = None,
            exit_code: Optional[int] = None,
            signals: Optional[tuple] = None,
            exit_fn: Optional[Callable[[int], None]] = None) -> None:
    """Install the preemption-notice signal handlers (idempotent;
    re-installing updates the overrides).

    ``grace_s`` / ``exit_code`` override the ``MXNET_PREEMPTION_GRACE_S``
    / ``MXNET_PREEMPTION_EXIT_CODE`` knobs for this process.  ``signals``
    defaults to ``(SIGTERM, SIGINT)``.  ``exit_fn(code)`` replaces the
    default exit (raising :class:`Preempted` in the main thread) — the
    in-process test hook; the grace watchdog always uses ``os._exit``
    (it runs off the main thread, where raising cannot work).  Must be
    called from the main thread (CPython delivers signals there)."""
    with _LOCK:
        _STATE["grace_s"] = grace_s
        _STATE["exit_code"] = exit_code
        _STATE["exit_fn"] = exit_fn
        if not _STATE["installed"]:
            prev: Dict[int, object] = {}
            for sig in signals or (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, notice)
            _STATE["prev_handlers"] = prev
            _STATE["installed"] = True


def uninstall() -> None:
    """Restore the pre-install signal handlers and clear the hooks +
    draining flag (tests)."""
    with _LOCK:
        for sig, h in dict(_STATE["prev_handlers"]).items():
            try:
                signal.signal(sig, h)
            except (ValueError, TypeError, OSError):
                pass
        _STATE["prev_handlers"] = {}
        _STATE["installed"] = False
    reset()
    del _DRAIN_HOOKS[:]


def reset() -> None:
    """Clear the draining flag and disarm the watchdog (tests — a unit
    test that took a notice must not leave every admission edge in the
    process shedding)."""
    _DRAINING.clear()
    wd = _STATE["watchdog"]
    _STATE["watchdog"] = None
    if wd is not None:
        wd.cancel()


def on_drain(fn: Callable[[], None]) -> Callable[[], None]:
    """Register a hook run AFTER ``engine.waitall()`` during the drain —
    the final-blocking-checkpoint slot (``run_elastic(preemption=...)``
    registers its save here).  Hooks run in registration order; a hook
    exception fails the drain (exit degrades to 1).  Returns ``fn`` so
    the caller can :func:`remove_drain_hook` it."""
    _DRAIN_HOOKS.append(fn)
    return fn


def remove_drain_hook(fn: Callable[[], None]) -> None:
    try:
        _DRAIN_HOOKS.remove(fn)
    except ValueError:
        pass


def drain() -> float:
    """Run the graceful drain NOW: the ``preemption.drain`` injection
    site, ``engine.waitall()`` (prefetch + deferred AMP + device
    metrics + checkpoint writers + serving/decode queues — admission
    edges already shed because :func:`draining` is set), then every
    :func:`on_drain` hook.  Returns the elapsed seconds (also set on
    the ``preemption.drain_s`` counter).  Raises on failure — the
    caller (:func:`notice`) degrades the exit code."""
    from . import engine as _engine
    from . import faults as _faults

    t0 = time.monotonic()
    _faults.inject("preemption.drain")
    _engine.waitall()
    for fn in list(_DRAIN_HOOKS):
        fn()
    secs = time.monotonic() - t0
    _DRAIN_S.set(secs)
    _telemetry.event("drain", "preemption", phase="complete",
                     drain_s=round(secs, 6))
    return secs


def _flush_telemetry() -> None:
    try:
        _telemetry.flush()
    except OSError:
        pass


def _do_exit(code: int) -> None:
    _flush_telemetry()
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except (OSError, ValueError):
        pass
    fn = _STATE["exit_fn"]
    if fn is not None:
        fn(code)
        return
    raise Preempted(code)


def _force_exit() -> None:
    """Grace-watchdog expiry: the drain wedged past the budget.  Runs
    off the main thread, so it cannot raise there — ``os._exit`` with
    exit_code + 1 (distinguished-but-degraded: the checkpoint may be
    stale).  An ``exit_fn`` override (tests) is honored instead."""
    if not _DRAINING.is_set():
        return
    code = exit_code() + 1
    _LOG.error("preemption drain exceeded the %.1fs grace budget; "
               "force-exiting %d", grace_s(), code)
    _telemetry.event("drain", "preemption", phase="grace_exceeded",
                     grace_s=grace_s())
    _flush_telemetry()
    fn = _STATE["exit_fn"]
    if fn is not None:
        fn(code)
        return
    os._exit(code)


def notice(signum: Optional[int] = None, frame: object = None) -> None:
    """The preemption-notice handler (also directly callable — tests and
    drills trigger it without a real signal).

    First notice: flip the draining flag, emit the ``drain`` event
    (stamped with the current train-step index), arm the grace
    watchdog, run :func:`drain`, then exit with the distinguished code
    (drain failure exits 1 instead).  A second notice while draining
    exits immediately — the supervisor escalated."""
    _NOTICES.inc()
    first = not _DRAINING.is_set()
    _DRAINING.set()
    if not first:
        _LOG.warning("second preemption notice while draining; "
                     "exiting immediately")
        _do_exit(exit_code())
        return
    g = grace_s()
    _telemetry.event("drain", "preemption", phase="notice",
                     sig=int(signum) if signum is not None else None,
                     grace_s=g)
    _LOG.warning("preemption notice (sig=%s): draining (grace %.1fs)",
                 signum, g)
    wd = None
    if g > 0:
        wd = threading.Timer(g, _force_exit)
        wd.daemon = True
        wd.start()
        _STATE["watchdog"] = wd
    code = exit_code()
    try:
        drain()
    except BaseException as e:
        from . import faults as _faults

        _faults.record_event("preemption.drain", "drain_failed", e)
        _LOG.error("preemption drain FAILED (%r); exiting 1 — do not "
                   "trust the newest checkpoint beyond its digest", e)
        code = 1
    finally:
        if wd is not None:
            wd.cancel()
            _STATE["watchdog"] = None
    _do_exit(code)
